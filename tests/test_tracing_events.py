"""util/tracing.py + util/events.py coverage: the span pipeline
(record -> flush -> get_spans -> chrome-trace JSON golden) and the
structured-event ring bounds (GCS ring + local tier).

Complements test_tracing.py (cluster-level span collection): these tests pin
the exact export format and the bounded-memory contracts.
"""

import json
import os

import pytest

import ray_tpu
from ray_tpu.util import events, tracing


@pytest.fixture
def traced_local():
    """Local-mode session with tracing on; restores flag + buffers after."""
    ray_tpu.shutdown()
    prev_env = os.environ.get("RAY_TPU_ENABLE_TRACING")
    tracing.enable()
    tracing.clear()
    ray_tpu.init(local_mode=True)
    tracing.clear()
    yield ray_tpu
    ray_tpu.shutdown()
    tracing.clear()
    if prev_env is None:
        os.environ.pop("RAY_TPU_ENABLE_TRACING", None)
    tracing._enabled = None


def test_chrome_trace_golden(traced_local, tmp_path):
    """record_span -> flush -> get_spans -> export writes exactly the
    chrome://tracing event this span describes (complete-event 'X' phase,
    microsecond units, extras under args)."""
    tracing.record_span("tokenize", 10.0, 10.25, category="user",
                        model="m1", shard=3)
    with tracing.profile("fwd", step=7):
        pass
    tracing.flush()
    spans = tracing.get_spans()
    assert [s["name"] for s in spans] == ["tokenize", "fwd"]

    path = str(tmp_path / "trace.json")
    assert tracing.export_chrome_trace(path) == 2
    data = json.load(open(path))
    assert set(data) == {"traceEvents"}
    ev = data["traceEvents"][0]
    golden = {
        "name": "tokenize",
        "cat": "user",
        "ph": "X",
        "ts": 10.0 * 1e6,
        "dur": 0.25 * 1e6,
        "args": {"model": "m1", "shard": 3},
    }
    assert {k: ev[k] for k in golden} == golden
    assert ev["pid"] == os.getpid() and isinstance(ev["tid"], int)
    fwd = data["traceEvents"][1]
    assert fwd["args"]["step"] == 7 and fwd["dur"] >= 0.0


def test_span_buffer_drop_oldest():
    """Pre-init spans accumulate in the process buffer, which is bounded:
    beyond _MAX_BUFFER the OLDEST spans fall off (tracing never leaks)."""
    ray_tpu.shutdown()
    tracing.enable()
    try:
        with tracing._lock:
            tracing._buffer.clear()
        total = tracing._MAX_BUFFER + 57
        for i in range(total):
            tracing.record_span(f"s{i}", float(i), float(i) + 1.0)
        with tracing._lock:
            names = [s["name"] for s in tracing._buffer]
        assert len(names) <= tracing._MAX_BUFFER
        assert f"s{total - 1}" in names  # newest kept
        assert "s0" not in names  # oldest dropped
    finally:
        with tracing._lock:
            tracing._buffer.clear()
        os.environ.pop("RAY_TPU_ENABLE_TRACING", None)
        tracing._enabled = None


def test_local_event_tier_and_severity_normalization(traced_local):
    events._local_events.clear()
    events.record("weights", "warning", "publish lagging", version=3)
    events.record("weights", "not-a-severity", "normalized")
    events.record("other", "error", "boom")
    evs = events.list_events(source="weights")
    assert [e["message"] for e in evs] == ["publish lagging", "normalized"]
    assert evs[0]["metadata"] == {"version": 3}
    assert evs[1]["severity"] == "INFO"  # unknown severities normalize
    assert [e["source"] for e in events.list_events(severity="ERROR")] \
        == ["other"]
    # limit takes the newest
    assert [e["source"] for e in events.list_events(limit=1)] == ["other"]


def test_event_ring_bounds_cluster():
    """The GCS keeps a bounded ring (1000): flooding it evicts the oldest
    events and never grows without bound."""
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2)
    try:
        for i in range(1040):
            events.record("flood", "info", f"e{i}", seq=i)
        evs = events.list_events(source="flood", limit=5000)
        assert len(evs) <= 1000
        seqs = [e["metadata"]["seq"] for e in evs]
        assert seqs[-1] == 1039  # newest survived
        assert 0 not in seqs  # oldest evicted
        assert seqs == sorted(seqs)
    finally:
        ray_tpu.shutdown()

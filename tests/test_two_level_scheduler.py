"""Two-level scheduling: local raylet grant + peer spillback via the synced
resource view, with no per-lease GCS round trip (reference:
cluster_lease_manager.cc:196 grant, :421 spillback; ray_syncer.h:89 views).
"""

import time

import pytest

from ray_tpu._private import wire
import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def two_node():
    ray_tpu.shutdown()
    cluster = Cluster(head_node_args={"resources": {"CPU": 2.0}})
    cluster.add_node(resources={"CPU": 2.0, "zone_b": 4.0})
    ray_tpu.init(address=cluster.address)
    cluster.wait_for_nodes(2)
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


def test_spillback_reaches_remote_resource(two_node):
    """The head raylet lacks zone_b entirely: the lease must spill to the
    peer via the raylet's cluster view (by totals), not via GCS PickNode."""
    @ray_tpu.remote(resources={"zone_b": 1.0}, num_cpus=0.1)
    def where():
        import os

        return os.getpid()

    pids = set(ray_tpu.get([where.remote() for _ in range(4)], timeout=120))
    assert pids  # executed somewhere — and only node_b carries zone_b


def test_spillback_on_busy_local(two_node):
    """With the local node saturated by long tasks, new tasks spill to the
    peer instead of queueing behind them."""
    @ray_tpu.remote(num_cpus=1.0)
    def hold(sec):
        time.sleep(sec)
        return "held"

    @ray_tpu.remote(num_cpus=1.0)
    def quick(i):
        return i

    # saturate both local CPUs for a while
    holders = [hold.remote(15.0) for _ in range(2)]
    time.sleep(2.0)  # let them occupy the local pool + heartbeat propagate
    t0 = time.monotonic()
    out = ray_tpu.get([quick.remote(i) for i in range(2)], timeout=120)
    dt = time.monotonic() - t0
    assert sorted(out) == [0, 1]
    # spilled tasks must not have waited for the 15s holders
    assert dt < 12.0, f"tasks queued behind saturated local node: {dt:.1f}s"
    ray_tpu.get(holders, timeout=120)


def test_raylet_view_tracks_membership(two_node):
    """A raylet's synced view includes peers and marks dead ones."""
    import pickle

    w = ray_tpu._private.worker.global_worker()

    def view():
        return wire.loads(w._run(w.raylet.call("GetNodeStats", b"")))

    stats = view()
    assert stats.get("cluster_view_size", 0) >= 2

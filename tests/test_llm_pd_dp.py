"""LLM serving patterns: prefill/decode disaggregation + DP serving.

Reference:
llm/_internal/serve/serving_patterns/prefill_decode/pd_server.py:31 and
.../data_parallel/{dp_server.py:14,dp_rank_assigner.py} — CPU tier with the
tiny model (SURVEY.md §4: accelerator features need a hardware-free tier).
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.llm.config import EngineConfig, LLMConfig, SamplingParams


def make_config(**ekw):
    eng = dict(max_num_seqs=4, max_model_len=128, page_size=16,
               prefill_bucket_min=16)
    eng.update(ekw)
    return LLMConfig(model_id="tiny", engine_config=EngineConfig(**eng),
                     model_overrides={"attention_impl": "xla"})


def test_kv_export_import_matches_monolithic():
    """Greedy completion via prefill-engine -> KV hand-off -> decode-engine
    must equal the monolithic engine's output exactly."""
    from ray_tpu.llm.engine import JaxLLMEngine

    mono = JaxLLMEngine(make_config(), seed=0)
    prompt = "the quick brown fox jumps"
    expect = mono.generate([prompt], SamplingParams(max_tokens=10))[0]

    prefill_eng = JaxLLMEngine(make_config(), seed=0)
    # decode engine shares weights (same seed) as a real deployment would
    decode_eng = JaxLLMEngine(make_config(), seed=0)
    state = prefill_eng.prefill_only(
        "r1", prompt, SamplingParams(max_tokens=10))
    assert state["generated"], "prefill must emit the first token"
    assert state["k"].shape[0] == mono.mcfg.n_layers
    # prefill engine released its slot/pages
    assert prefill_eng.num_active() == 0
    decode_eng.add_request_with_kv(state)
    done = None
    while done is None:
        for out in decode_eng.step():
            if out.finished:
                done = out
    assert done.token_ids == expect.token_ids
    assert done.finish_reason == expect.finish_reason


def test_prefill_only_single_token_request():
    from ray_tpu.llm.engine import JaxLLMEngine

    eng = JaxLLMEngine(make_config(), seed=0)
    state = eng.prefill_only("r1", "hello", SamplingParams(max_tokens=1))
    assert state["finished"] and state["finish_reason"] == "length"
    assert len(state["generated"]) == 1


@pytest.fixture
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield ray_tpu
    ray_tpu.shutdown()


def test_pd_actors_end_to_end(cluster):
    """Prefill replica + decode replica serve a completion end-to-end
    (the round-2 verdict's done criterion)."""
    import cloudpickle

    from ray_tpu.llm.engine import JaxLLMEngine
    from ray_tpu.llm.pd import DecodeWorker, PrefillWorker

    cfg = make_config()
    mono = JaxLLMEngine(cfg, seed=0)
    prompt = "hello distributed serving"
    expect = mono.generate([prompt], SamplingParams(max_tokens=8))[0]
    blob = cloudpickle.dumps(mono.params)

    p = ray_tpu.remote(num_cpus=0.5)(PrefillWorker).remote(cfg, blob)
    d = ray_tpu.remote(num_cpus=0.5)(DecodeWorker).remote(cfg, blob)
    state = ray_tpu.get(
        p.prefill.remote(prompt, SamplingParams(max_tokens=8)), timeout=300)
    out = ray_tpu.get(d.decode.remote(state), timeout=300)
    assert out["token_ids"] == expect.token_ids
    assert out["finish_reason"] == expect.finish_reason
    # division of labor: prefill engine never decoded, decode never prefilled
    pm = ray_tpu.get(p.metrics.remote(), timeout=60)
    dm = ray_tpu.get(d.metrics.remote(), timeout=60)
    assert pm["prefill_tokens"] > 0 and pm["decode_steps"] == 0
    assert dm["decode_steps"] > 0 and dm["prefill_tokens"] == 0


def test_dp_replicas_get_distinct_ranks_and_spread(cluster):
    """Router spreads completions across 2 DP engine replicas, each holding
    a distinct dp rank."""
    from ray_tpu.llm.pd import build_dp_openai_app

    handle = build_dp_openai_app(make_config(), dp_size=2)
    seen_ranks = set()
    for i in range(8):
        out = ray_tpu.get(handle.remote({"prompt": f"ping {i}",
                                         "max_tokens": 2}), timeout=300)
        assert out["choices"][0]["text"] is not None
        seen_ranks.add(out["dp_rank"])
    assert seen_ranks == {0, 1}, f"router did not spread: {seen_ranks}"

"""Serve tests (reference tier: python/ray/serve/tests basics)."""

import time

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=6)
    yield ray_tpu
    try:
        serve.shutdown()
    except Exception:
        pass
    ray_tpu.shutdown()


def test_function_deployment(cluster):
    @serve.deployment
    def doubler(body):
        return body["x"] * 2

    handle = serve.run(doubler.bind())
    assert ray_tpu.get(handle.remote({"x": 21}), timeout=120) == 42
    serve.delete("doubler")


def test_class_deployment_replicas_and_status(cluster):
    @serve.deployment(num_replicas=2)
    class Counter:
        def __init__(self, start):
            self.n = start

        def __call__(self, body):
            self.n += 1
            return {"pid_count": self.n, "base": self.n}

        def peek(self, body=None):
            return self.n

    handle = serve.run(Counter.bind(10))
    outs = ray_tpu.get([handle.remote({}) for _ in range(6)], timeout=120)
    assert all(o["base"] >= 11 for o in outs)
    st = serve.status()
    assert st["Counter"]["num_replicas"] == 2
    # method routing
    peek = handle.options(method_name="peek")
    assert ray_tpu.get(peek.remote(), timeout=60) >= 10
    serve.delete("Counter")


def test_model_composition(cluster):
    @serve.deployment
    class Child:
        def __call__(self, body):
            return body["v"] + 1

    @serve.deployment
    class Parent:
        def __init__(self, child):
            self.child = child

        def __call__(self, body):
            inner = ray_tpu.get(self.child.remote({"v": body["v"]}))
            return inner * 10

    child_app = Child.bind()
    serve.run(child_app)
    handle = serve.run(Parent.bind(child_app))
    assert ray_tpu.get(handle.remote({"v": 4}), timeout=120) == 50
    serve.delete("Parent")
    serve.delete("Child")


def test_batching(cluster):
    @serve.deployment
    class Batched:
        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.05)
        async def __call__(self, bodies):
            # one invocation sees multiple queued requests
            n = len(bodies)
            return [{"batch_size": n, "x": b["x"]} for b in bodies]

    handle = serve.run(Batched.bind())
    refs = [handle.remote({"x": i}) for i in range(4)]
    outs = ray_tpu.get(refs, timeout=120)
    assert {o["x"] for o in outs} == {0, 1, 2, 3}
    assert max(o["batch_size"] for o in outs) >= 2
    serve.delete("Batched")


def test_replica_restart_on_death(cluster):
    import os

    @serve.deployment
    class Fragile:
        def __call__(self, body):
            if body.get("die"):
                os._exit(1)
            return "alive"

    handle = serve.run(Fragile.bind())
    assert ray_tpu.get(handle.remote({}), timeout=120) == "alive"
    try:
        ray_tpu.get(handle.remote({"die": True}), timeout=60)
    except Exception:
        pass
    # controller reconciles on demand
    controller = ray_tpu.get_actor("serve_controller")
    deadline = time.time() + 60
    ok = False
    while time.time() < deadline:
        ray_tpu.get(controller.check_replicas.remote(), timeout=60)
        handle._refresh(force=True)
        try:
            if ray_tpu.get(handle.remote({}), timeout=30) == "alive":
                ok = True
                break
        except Exception:
            time.sleep(0.5)
    assert ok
    serve.delete("Fragile")


def test_http_proxy(cluster):
    import json
    import urllib.request

    @serve.deployment
    def echo(body):
        return {"echo": body}

    serve.run(echo.bind())
    port = serve.start_http_proxy()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/echo", data=json.dumps({"hi": 1}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as resp:
        out = json.loads(resp.read())
    assert out["result"]["echo"] == {"hi": 1}
    serve.delete("echo")


def test_replica_peak_sampling_under_stats_lock():
    """Regression (raylint RCE001): _Replica's ongoing/peak counters are
    mutated on the replica's event loop but take_ongoing_peak() runs on a
    sync actor-pool thread, and its read-reset is a two-step RMW. The
    stats lock keeps a burst that fully drains between two autoscaler
    polls from being silently dropped. No cluster: the replica is driven
    directly on a private event loop."""
    import asyncio
    import threading

    import cloudpickle

    from ray_tpu.serve.api import _Replica

    class SlowTarget:
        def __init__(self):
            self.gate = asyncio.Event()

        async def __call__(self):
            await self.gate.wait()
            return "ok"

    loop = asyncio.new_event_loop()
    runner = threading.Thread(target=loop.run_forever, daemon=True)
    runner.start()
    try:
        replica = _Replica.cls(cloudpickle.dumps(SlowTarget),
                               cloudpickle.dumps(((), {})))
        args_blob = cloudpickle.dumps(((), {}))
        futs = [asyncio.run_coroutine_threadsafe(
            replica.handle_request("__call__", args_blob), loop)
            for _ in range(3)]
        deadline = time.monotonic() + 10
        while replica.num_ongoing() < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert replica.num_ongoing() == 3
        # the burst drains COMPLETELY before the autoscaler's next poll...
        loop.call_soon_threadsafe(replica._callable.gate.set)
        assert [f.result(10) for f in futs] == ["ok"] * 3
        assert replica.num_ongoing() == 0
        # ...yet the poll still sees its high-water mark, exactly once
        assert replica.take_ongoing_peak() == 3
        assert replica.take_ongoing_peak() == 0
    finally:
        loop.call_soon_threadsafe(loop.stop)
        runner.join(5)
        loop.close()

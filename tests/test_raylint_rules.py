"""Unit fixtures for each raylint rule: one positive and one negative case
per rule, plus the suppression-comment and baseline mechanisms."""

import sys
import textwrap
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parents[1]
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

import pytest  # noqa: E402

from tools.raylint import core  # noqa: E402


def lint(src, relpath="ray_tpu/_private/mod.py", root=REPO_ROOT, rules=None):
    project = core.Project(root, rule_names=rules)
    return project.check_source(textwrap.dedent(src), relpath)


def rules_of(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# ASY001 — blocking call in async body
# ---------------------------------------------------------------------------


def test_asy001_positive():
    findings = lint("""
        import time
        from time import sleep as zzz
        import subprocess

        async def f(self):
            time.sleep(0.5)
            zzz(1)
            subprocess.check_output(["ls"])
    """, rules=["ASY001"])
    assert rules_of(findings) == ["ASY001"] * 3
    # import aliasing is resolved back to the real callable
    assert "time.sleep" in findings[1].message


def test_asy001_blocking_cluster_wait():
    findings = lint("""
        import ray_tpu

        async def f(refs):
            return ray_tpu.get(refs)
    """, rules=["ASY001"])
    assert rules_of(findings) == ["ASY001"]


def test_asy001_negative():
    findings = lint("""
        import asyncio
        import time

        def sync_fn():
            time.sleep(1)          # sync context: fine

        async def f(loop):
            await asyncio.sleep(1)
            # blocking work pushed off-loop is the sanctioned pattern
            await loop.run_in_executor(None, lambda: time.sleep(1))

        async def g(self):
            def thunk():
                time.sleep(2)      # nested sync def: runs in an executor
            return thunk
    """, rules=["ASY001"])
    assert findings == []


# ---------------------------------------------------------------------------
# ASY002 — threading primitives in async context
# ---------------------------------------------------------------------------


def test_asy002_positive_await_under_lock():
    findings = lint("""
        import asyncio

        async def f(self):
            with self._lock:
                await asyncio.sleep(0)
    """, rules=["ASY002"])
    assert rules_of(findings) == ["ASY002"]


def test_asy002_positive_threading_primitive():
    findings = lint("""
        import threading

        async def f():
            ev = threading.Event()
            return ev
    """, rules=["ASY002"])
    assert rules_of(findings) == ["ASY002"]


def test_asy002_negative():
    findings = lint("""
        import asyncio
        import threading

        def sync_fn(self):
            with self._lock:       # no loop on this thread
                return 1

        async def f(self):
            with self._lock:       # no await inside: bounded hold
                x = 1
            async with self._alock:
                await asyncio.sleep(0)

        def make():
            return threading.Lock()
    """, rules=["ASY002"])
    assert findings == []


# ---------------------------------------------------------------------------
# SER001 — unpickle outside the serialization boundary
# ---------------------------------------------------------------------------


def test_ser001_positive():
    src = """
        import pickle
        import cloudpickle

        def decode(blob):
            a = pickle.loads(blob)
            return cloudpickle.loads(blob)
    """
    findings = lint(src, relpath="ray_tpu/util/foo.py", rules=["SER001"])
    assert rules_of(findings) == ["SER001", "SER001"]


def test_ser001_negative_allowlisted_boundary():
    src = """
        import pickle

        def deserialize(blob):
            return pickle.loads(blob)
    """
    findings = lint(src, relpath="ray_tpu/_private/serialization.py",
                    rules=["SER001"])
    assert findings == []


# ---------------------------------------------------------------------------
# EXC001 — swallowed exceptions on control-plane paths
# ---------------------------------------------------------------------------


def test_exc001_positive():
    findings = lint("""
        def f():
            try:
                g()
            except Exception:
                pass
            try:
                g()
            except (OSError, ValueError):
                ...
    """, rules=["EXC001"])
    assert rules_of(findings) == ["EXC001", "EXC001"]


def test_exc001_break_and_bare_return_also_swallow():
    findings = lint("""
        def f(items):
            for i in items:
                try:
                    g(i)
                except Exception:
                    break
            try:
                g(0)
            except Exception:
                return
            try:
                g(1)
            except Exception:
                return fallback()   # uses the failure: not a silent swallow
    """, rules=["EXC001"])
    assert rules_of(findings) == ["EXC001", "EXC001"]


def test_exc001_negative_logged_or_narrow_or_offplane():
    # a log call, a narrow control-flow catch, and a non-control-plane path
    # are all fine
    clean = """
        import logging
        logger = logging.getLogger(__name__)

        def f(d):
            try:
                g()
            except Exception as e:
                logger.debug("g failed: %s", e)
            try:
                del d["k"]
            except KeyError:
                pass
            try:
                h()
            except asyncio.TimeoutError:
                pass
    """
    assert lint(clean, rules=["EXC001"]) == []
    swallowing = """
        def f():
            try:
                g()
            except Exception:
                pass
    """
    assert lint(swallowing, relpath="ray_tpu/serve/mod.py",
                rules=["EXC001"]) == []


# ---------------------------------------------------------------------------
# TRC001 — JAX tracers escaping into actor/object state
# ---------------------------------------------------------------------------


def test_trc001_self_store_in_jit_decorated():
    findings = lint("""
        import jax

        class Learner:
            @jax.jit
            def step(self, params, batch):
                grads = jax.grad(loss)(params, batch)
                self.last_grads = grads      # tracer -> actor state
                return grads
    """, rules=["TRC001"])
    assert rules_of(findings) == ["TRC001"]
    assert "self.last_grads" in findings[0].message


def test_trc001_partial_jit_and_aliased_import():
    findings = lint("""
        from functools import partial
        from jax import jit as jj

        class M:
            @partial(jj, static_argnums=0)
            def fwd(self, x):
                self.cache = x * 2
                return x
    """, rules=["TRC001"])
    assert rules_of(findings) == ["TRC001"]


def test_trc001_remote_and_put_in_jit_target():
    findings = lint("""
        import jax
        import ray_tpu

        def train_step(state, batch, actor):
            actor.update.remote(state)       # tracer into a task arg
            ray_tpu.put(batch)               # tracer into the object plane
            return state

        train_step = jax.jit(train_step, donate_argnums=0)
    """, rules=["TRC001"])
    assert rules_of(findings) == ["TRC001", "TRC001"]
    assert ".remote" in findings[0].message
    assert "object plane" in findings[1].message


def test_trc001_method_handed_to_jit_via_attribute():
    findings = lint("""
        import jax

        class Engine:
            def __init__(self):
                self._step = jax.jit(self._step_impl)

            def _step_impl(self, params, toks):
                self.params = params
                return toks
    """, rules=["TRC001"])
    assert rules_of(findings) == ["TRC001"]


def test_trc001_negative_untraced_and_constants():
    findings = lint("""
        import jax

        class Learner:
            def update(self, batch):
                # sync wrapper OUTSIDE the trace: storing results is fine
                self.metrics = self._jitted(batch)
                self.ready = True

            @jax.jit
            def _jitted(self, batch):
                self.flag = True             # plain constant: not a tracer
                local = batch * 2            # locals never escape
                return local
    """, rules=["TRC001"])
    assert rules_of(findings) == []


def test_trc001_suppression():
    findings = lint("""
        import jax

        class M:
            @jax.jit
            def f(self, x):
                self.x = x  # raylint: disable=TRC001 concrete under disable_jit in tests
                return x
    """, rules=["TRC001"])
    assert rules_of(findings) == []


# ---------------------------------------------------------------------------
# WIRE001 — unregistered wire structs
# ---------------------------------------------------------------------------


@pytest.fixture
def wire_root(tmp_path):
    private = tmp_path / "ray_tpu" / "_private"
    private.mkdir(parents=True)
    (private / "wire.py").write_text(textwrap.dedent("""
        def register_struct(cls, **kw):
            return cls

        def register_id(cls, **kw):
            return cls

        def _register_builtin_types():
            from ray_tpu._private import common
            for c in (common.Registered, common.AlsoRegistered):
                register_struct(c)
    """))
    return tmp_path


def test_wire001_positive(wire_root):
    findings = lint("""
        from dataclasses import dataclass

        @dataclass
        class Registered:
            a: int = 0

        @dataclass
        class Orphan:
            b: int = 0
    """, relpath="ray_tpu/_private/common.py", root=wire_root,
        rules=["WIRE001"])
    assert rules_of(findings) == ["WIRE001"]
    assert "Orphan" in findings[0].message


def test_wire001_negative(wire_root):
    findings = lint("""
        from dataclasses import dataclass

        @dataclass
        class Registered:
            a: int = 0

        class NotWireData:
            pass
    """, relpath="ray_tpu/_private/common.py", root=wire_root,
        rules=["WIRE001"])
    assert findings == []


# ---------------------------------------------------------------------------
# suppression comments
# ---------------------------------------------------------------------------


def test_suppression_same_line_and_above():
    findings = lint("""
        import time

        async def f():
            time.sleep(1)  # raylint: disable=ASY001 measured: shorter than a loop tick
            # raylint: disable=ASY001 warmup path, loop not serving yet
            time.sleep(2)
            time.sleep(3)
    """, rules=["ASY001"])
    assert len(findings) == 1 and findings[0].line == 8


def test_suppression_above_decorator_reaches_the_def_line(wire_root):
    # WIRE001 findings anchor at the `class` line (py3.8+ AST excludes
    # decorators from lineno); a directive above the decorator must still
    # suppress them
    findings = lint("""
        from dataclasses import dataclass

        # raylint: disable=WIRE001 process-local record
        @dataclass
        class Orphan:
            b: int = 0
    """, relpath="ray_tpu/_private/common.py", root=wire_root,
        rules=["WIRE001"])
    assert findings == []


def test_suppression_is_rule_specific():
    findings = lint("""
        import time

        async def f():
            time.sleep(1)  # raylint: disable=EXC001 wrong rule id
    """, rules=["ASY001"])
    assert rules_of(findings) == ["ASY001"]


def test_suppression_filewide_and_all():
    src = """
        # raylint: disable-file=ASY001
        import time

        async def f():
            time.sleep(1)
            x = 1  # raylint: disable=all
    """
    assert lint(src, rules=["ASY001"]) == []


def test_directive_does_not_bind_across_blank_lines():
    # a stale directive must not drift onto unrelated code below a gap
    findings = lint("""
        import time

        # raylint: disable=ASY001 the line this covered was deleted

        async def f():
            time.sleep(1)
    """, rules=["ASY001"])
    assert rules_of(findings) == ["ASY001"]


def test_rules_subset_does_not_report_other_rules_stale(tmp_path):
    mod = tmp_path / "_private" / "mod.py"
    mod.parent.mkdir()
    mod.write_text("import pickle\n\ndef f(b):\n    return pickle.loads(b)\n")
    full = core.check_paths([mod.parent], tmp_path)
    baseline_path = tmp_path / "baseline.json"
    baseline_path.write_text(core.dump_baseline(full.findings))  # SER001 entry
    sub = core.check_paths([mod.parent], tmp_path,
                           baseline=core.load_baseline(baseline_path),
                           rule_names=["ASY001"])
    assert sub.passed and not sub.unused_baseline


def test_directive_inside_string_is_inert():
    findings = lint('''
        import time

        DOC = "# raylint: disable-file=ASY001"

        async def f():
            time.sleep(1)
    ''', rules=["ASY001"])
    assert rules_of(findings) == ["ASY001"]


# ---------------------------------------------------------------------------
# baseline mechanism
# ---------------------------------------------------------------------------


def test_baseline_grandfathers_exact_findings(tmp_path):
    mod = tmp_path / "_private" / "mod.py"
    mod.parent.mkdir()
    mod.write_text(textwrap.dedent("""
        import time

        async def old():
            time.sleep(1)
    """))
    report = core.check_paths([mod.parent], tmp_path)
    assert len(report.findings) == 1

    baseline_doc = core.dump_baseline(report.findings)
    baseline_path = tmp_path / "baseline.json"
    baseline_path.write_text(baseline_doc)
    baseline = core.load_baseline(baseline_path)

    # same code: clean, nothing stale
    report2 = core.check_paths([mod.parent], tmp_path, baseline=baseline)
    assert report2.ok and not report2.unused_baseline
    assert len(report2.baselined) == 1

    # a NEW occurrence of the same pattern on a new line still fails
    mod.write_text(mod.read_text() + "\nasync def new():\n    time.sleep(2)\n")
    report3 = core.check_paths([mod.parent], tmp_path, baseline=baseline)
    assert len(report3.findings) == 1
    assert "time.sleep(2)" in report3.findings[0].snippet

    # the baselined finding survives line drift (prepended code)
    mod.write_text("X = 1\n" + textwrap.dedent("""
        import time

        async def old():
            time.sleep(1)
    """))
    report4 = core.check_paths([mod.parent], tmp_path, baseline=baseline)
    assert report4.ok, [f.render() for f in report4.findings]


def test_baseline_reports_stale_entries(tmp_path):
    mod = tmp_path / "_private" / "mod.py"
    mod.parent.mkdir()
    mod.write_text("import time\nasync def f():\n    time.sleep(1)\n")
    report = core.check_paths([mod.parent], tmp_path)
    baseline_path = tmp_path / "baseline.json"
    baseline_path.write_text(core.dump_baseline(report.findings))

    mod.write_text("import asyncio\nasync def f():\n    await asyncio.sleep(1)\n")
    report2 = core.check_paths([mod.parent], tmp_path,
                               baseline=core.load_baseline(baseline_path))
    assert report2.ok
    assert len(report2.unused_baseline) == 1


def test_parse_error_is_a_finding():
    findings = lint("def broken(:\n    pass\n")
    assert rules_of(findings) == [core.PARSE_ERROR_RULE]
    # NUL bytes raise ValueError (not SyntaxError) from ast.parse on
    # py<=3.11; must still be a finding, not a crash
    findings = lint("x = 1\x00\n")
    assert rules_of(findings) == [core.PARSE_ERROR_RULE]


def test_overlapping_paths_lint_each_file_once(tmp_path):
    mod = tmp_path / "_private" / "mod.py"
    mod.parent.mkdir()
    mod.write_text("import time\nasync def f():\n    time.sleep(1)\n")
    report = core.check_paths([tmp_path, mod.parent, mod], tmp_path)
    assert report.files_checked == 1
    assert len(report.findings) == 1


# ---------------------------------------------------------------------------
# ASY003 — leaked asyncio tasks
# ---------------------------------------------------------------------------


def test_asy003_positive_bare_statements():
    findings = lint("""
        import asyncio

        class S:
            def kick(self):
                asyncio.ensure_future(self._work())

            def kick2(self):
                asyncio.create_task(self._work())

            def kick3(self):
                self.loop.create_task(self._work())
    """, rules=["ASY003"])
    assert rules_of(findings) == ["ASY003"] * 3
    assert "done-callback" in findings[0].message


def test_asy003_positive_lambda_callback():
    findings = lint("""
        import asyncio

        def arm(loop, client):
            loop.call_later(30.0, lambda: asyncio.ensure_future(client.close()))
    """, rules=["ASY003"])
    assert rules_of(findings) == ["ASY003"]


def test_asy003_positive_append_to_longlived_state():
    """`self._background.append(ensure_future(...))` keeps a handle but
    nobody ever awaits a shutdown-only list: failures stay silent. The
    tightened rule catches the shape (and `.add` on sets)."""
    findings = lint("""
        import asyncio

        class S:
            async def start(self):
                self._background.append(asyncio.ensure_future(self._loop()))
                self._tasks.add(asyncio.create_task(self._flush()))
    """, rules=["ASY003"])
    assert rules_of(findings) == ["ASY003"] * 2
    assert "long-lived state" in findings[0].message


def test_asy003_negative_owned_tasks():
    findings = lint("""
        import asyncio
        from ray_tpu._private.async_util import spawn

        class S:
            async def run(self):
                t = asyncio.ensure_future(self._work())       # stored
                # spawn() already logs failures; appending ITS handle is fine
                self._background.append(spawn(self._loop(), what="loop"))
                await asyncio.ensure_future(self._work())     # awaited
                asyncio.ensure_future(self._work()).add_done_callback(self._cb)
                spawn(self._work(), what="sanctioned helper")
                # a LOCAL list is awaited in-scope: allowed
                waiters = []
                waiters.append(asyncio.ensure_future(self._work()))
                await asyncio.wait(waiters)
                return t
    """, rules=["ASY003"])
    assert rules_of(findings) == []


def test_asy003_suppression():
    findings = lint("""
        import asyncio

        def kick(self):
            asyncio.ensure_future(self._work())  # raylint: disable=ASY003 guarded internally
    """, rules=["ASY003"])
    assert rules_of(findings) == []


# ---------------------------------------------------------------------------
# LCK001 — lock-order inversions across the control-plane hierarchy
# ---------------------------------------------------------------------------


def test_lck001_positive_inverted_nesting():
    """Taking a GCS-tier lock while holding a core-worker-tier lock runs
    AGAINST the GCS -> raylet -> core-worker order."""
    findings = lint("""
        class S:
            def bad(self):
                with self._core_worker_lock:
                    with self._gcs_lock:
                        self.sync()
    """, rules=["LCK001"])
    assert rules_of(findings) == ["LCK001"]
    assert "GCS -> raylet -> core worker" in findings[0].message


def test_lck001_positive_single_with_multiple_items():
    """`with a, b:` acquires left-to-right — the one-line form of the same
    inversion must be flagged too."""
    findings = lint("""
        class S:
            def bad(self):
                with self._core_worker_lock, self._gcs_lock:
                    self.sync()
    """, rules=["LCK001"])
    assert rules_of(findings) == ["LCK001"]


def test_lck001_positive_raylet_under_worker_async():
    findings = lint("""
        class S:
            async def bad(self):
                async with self._worker_lock:
                    async with self.raylet_mutex:
                        await self.push()
    """, rules=["LCK001"])
    assert rules_of(findings) == ["LCK001"]


def test_lck001_negative_ordered_and_untier():
    findings = lint("""
        class S:
            def ok(self):
                # down the hierarchy: allowed
                with self._gcs_lock:
                    with self._raylet_lock:
                        with self._core_worker_lock:
                            self.sync()

            def ok2(self):
                # untiered locks are out of scope
                with self._exec_lock:
                    with self._state_lock:
                        self.run()

            def ok3(self):
                # sequential (not nested) acquisitions are fine
                with self._core_worker_lock:
                    self.a()
                with self._gcs_lock:
                    self.b()
    """, rules=["LCK001"])
    assert rules_of(findings) == []


def test_lck001_nested_def_resets_the_held_stack():
    """A nested function runs on its own call path: holding a worker lock
    while DEFINING a closure that takes a GCS lock is not an inversion."""
    findings = lint("""
        class S:
            def ok(self):
                with self._worker_lock:
                    def flush():
                        with self._gcs_lock:
                            self.sync()
                    return flush
    """, rules=["LCK001"])
    assert rules_of(findings) == []


def test_lck001_suppression():
    findings = lint("""
        class S:
            def audited(self):
                with self._core_worker_lock:
                    with self._gcs_lock:  # raylint: disable=LCK001 shutdown-only path, single-threaded
                        self.sync()
    """, rules=["LCK001"])
    assert rules_of(findings) == []


# ---------------------------------------------------------------------------
# CKP001 — checkpoint-plane writes outside the atomic-commit helper
# ---------------------------------------------------------------------------


def test_ckp001_positive_write_open_and_dump():
    findings = lint("""
        import json

        def save_state(path, state):
            with open(path, "w") as f:
                json.dump(state, f)

        def save_blob(path, blob):
            with open(path, mode="wb") as f:
                f.write(blob)
    """, relpath="ray_tpu/ckpt/foo.py", rules=["CKP001"])
    assert rules_of(findings) == ["CKP001"] * 3
    assert "atomic_write" in findings[0].message


def test_ckp001_positive_pathlib_and_train_manager():
    findings = lint("""
        from pathlib import Path

        def save(p, data):
            Path(p).write_bytes(data)
    """, relpath="ray_tpu/train/checkpoint.py", rules=["CKP001"])
    assert rules_of(findings) == ["CKP001"]


def test_ckp001_negative_reads_helper_and_other_paths():
    # read-mode opens on plane paths are fine
    findings = lint("""
        import json

        def load(path):
            with open(path) as f:
                return json.load(f)

        def load_bytes(path):
            with open(path, "rb") as f:
                return f.read()
    """, relpath="ray_tpu/ckpt/foo.py", rules=["CKP001"])
    assert rules_of(findings) == []
    # the helper itself carries the one sanctioned raw write (suppressed)
    findings = lint("""
        import os

        def atomic_write(path, data):
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:  # raylint: disable=CKP001 this IS the helper
                f.write(data)
                os.fsync(f.fileno())
            os.replace(tmp, path)
    """, relpath="ray_tpu/ckpt/manifest.py", rules=["CKP001"])
    assert rules_of(findings) == []
    # writes OUTSIDE checkpoint-plane paths are not this rule's business
    findings = lint("""
        def log(path, line):
            with open(path, "a") as f:
                f.write(line)
    """, relpath="ray_tpu/_private/logs.py", rules=["CKP001"])
    assert rules_of(findings) == []


def test_ckp001_nonconstant_mode_is_conservative():
    findings = lint("""
        def copy(path, mode):
            with open(path, mode) as f:
                return f
    """, relpath="ray_tpu/ckpt/foo.py", rules=["CKP001"])
    assert rules_of(findings) == ["CKP001"]


def test_ckp001_backend_write_method_with_fsync_rename_is_exempt():
    # a storage backend's designated write chokepoint may write directly —
    # when the method itself upholds the temp+fsync+rename contract
    findings = lint("""
        import os

        class DirBucketClient:
            def put_object(self, key, data):
                tmp = key + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(data)
                    os.fsync(f.fileno())
                os.replace(tmp, key)

        class BucketBackend:
            def complete_multipart(self, parts, path):
                tmp = path + ".tmp"
                with open(tmp, "wb") as out:
                    for p in parts:
                        with open(p, "rb") as f:
                            out.write(f.read())
                    os.fsync(out.fileno())
                os.replace(tmp, path)
    """, relpath="ray_tpu/ckpt/tier/bucket.py", rules=["CKP001"])
    assert rules_of(findings) == []


def test_ckp001_backend_write_method_without_contract_flags():
    # same chokepoint method, but no fsync+rename: the exemption does not
    # apply — a torn backend object is as fatal as a torn manifest
    findings = lint("""
        import os

        class FlakyBackend:
            def put(self, h, data):
                with open(h, "wb") as f:
                    f.write(data)
    """, relpath="ray_tpu/ckpt/tier/flaky.py", rules=["CKP001"])
    assert rules_of(findings) == ["CKP001"]


def test_ckp001_backend_nonwrite_method_and_nonbackend_class_flag():
    findings = lint("""
        import os

        class DirBucketClient:
            def snapshot(self, path, data):  # not a designated write method
                with open(path, "wb") as f:
                    f.write(data)
                    os.fsync(f.fileno())
                os.replace(path, path + ".bak")

        class Indexer:  # not a Backend/BucketClient class
            def put_object(self, path, data):
                with open(path, "wb") as f:
                    f.write(data)
                    os.fsync(f.fileno())
                os.replace(path, path + ".new")
    """, relpath="ray_tpu/ckpt/tier/bucket.py", rules=["CKP001"])
    assert rules_of(findings) == ["CKP001"] * 2


def test_ckp001_backend_suppression():
    findings = lint("""
        class RamBackend:
            def put(self, h, data):
                with open("/dev/shm/" + h, "wb") as f:  # raylint: disable=CKP001 tmpfs scratch tier, loss is by design
                    f.write(data)
    """, relpath="ray_tpu/ckpt/tier/ram.py", rules=["CKP001"])
    assert rules_of(findings) == []


# ---------------------------------------------------------------------------
# ASY004 — transitive blocking calls (graph-based; generalizes ASY001)
# ---------------------------------------------------------------------------


def test_asy004_positive_two_hop_chain(tmp_path):
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        import time

        def _do_io():
            time.sleep(1)

        def _helper(self):
            return _do_io()

        class Server:
            async def handler(self, req):
                self._sync_step()
                return req

            def _sync_step(self):
                _helper(self)
    """, relpath="ray_tpu/_private/svc.py", root=tmp_path, rules=["ASY004"])
    assert rules_of(findings) == ["ASY004"]
    # the chain names every hop down to the blocking call
    assert "time.sleep" in findings[0].message
    assert "_do_io" in findings[0].message
    # anchored at the async function's call site, not the leaf helper
    assert findings[0].line != 0


def test_asy004_negative_direct_async_and_executor(tmp_path):
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        import asyncio
        import time

        def _blocking():
            time.sleep(1)

        async def ok(loop):
            # handing the chain to an executor is the sanctioned pattern
            await loop.run_in_executor(None, _blocking)
            await asyncio.sleep(0)

        def plain_sync():
            _blocking()  # sync caller: not this rule's business
    """, relpath="ray_tpu/_private/svc.py", root=tmp_path, rules=["ASY004"])
    assert findings == []


def test_asy004_direct_blocking_is_asy001s_not_asy004s(tmp_path):
    # a DIRECT blocking call has no helper chain: ASY001 territory, so the
    # two rules never double-report one site
    (tmp_path / "ray_tpu").mkdir(parents=True)
    src = """
        import time

        async def f():
            time.sleep(1)
    """
    only4 = lint(src, relpath="ray_tpu/_private/svc.py", root=tmp_path,
                 rules=["ASY004"])
    assert only4 == []
    only1 = lint(src, relpath="ray_tpu/_private/svc.py", root=tmp_path,
                 rules=["ASY001"])
    assert rules_of(only1) == ["ASY001"]


def test_asy004_suppression(tmp_path):
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        import time

        def _warmup():
            time.sleep(0.1)

        async def boot():
            _warmup()  # raylint: disable=ASY004 one-time startup, loop idle
    """, relpath="ray_tpu/_private/svc.py", root=tmp_path, rules=["ASY004"])
    assert findings == []


def test_asy004_cross_module_chain(tmp_path):
    # the chain crosses a module boundary via an imported helper
    pkg = tmp_path / "ray_tpu" / "_private"
    pkg.mkdir(parents=True)
    (pkg / "util_mod.py").write_text(textwrap.dedent("""
        import subprocess

        def run_tool():
            subprocess.check_output(["ls"])
    """))
    findings = lint("""
        from ray_tpu._private.util_mod import run_tool

        async def handler():
            run_tool()
    """, relpath="ray_tpu/_private/svc.py", root=tmp_path, rules=["ASY004"])
    assert rules_of(findings) == ["ASY004"]
    assert "subprocess.check_output" in findings[0].message


# ---------------------------------------------------------------------------
# LCK002 — lock-order cycles in the global acquisition graph
# ---------------------------------------------------------------------------


def test_lck002_positive_abba_cycle_through_helpers(tmp_path):
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        import threading

        class Planes:
            def __init__(self):
                self._sched_lock = threading.Lock()
                self._table_lock = threading.Lock()

            def path_one(self):
                with self._sched_lock:
                    self._touch_table()

            def _touch_table(self):
                with self._table_lock:
                    pass

            def path_two(self):
                with self._table_lock:
                    with self._sched_lock:
                        pass
    """, relpath="ray_tpu/_private/planes.py", root=tmp_path,
        rules=["LCK002"])
    assert "LCK002" in rules_of(findings)
    assert any("cycle" in f.message for f in findings)


def test_lck002_positive_self_deadlock_via_helper(tmp_path):
    # a non-reentrant lock re-acquired through a helper call is a
    # self-deadlock the lexical rules cannot see
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()

            def put(self, k, v):
                with self._lock:
                    self._evict()

            def _evict(self):
                with self._lock:
                    pass
    """, relpath="ray_tpu/_private/store_mod.py", root=tmp_path,
        rules=["LCK002"])
    assert rules_of(findings) == ["LCK002"]
    assert "re-acquired" in findings[0].message


def test_lck002_negative_consistent_order_and_rlock(tmp_path):
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        import threading

        class Planes:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()
                self._re_lock = threading.RLock()

            def one(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def two(self):
                with self._a_lock:
                    self._inner()

            def _inner(self):
                with self._b_lock:
                    pass

            def reentrant(self):
                with self._re_lock:
                    self._again()

            def _again(self):
                with self._re_lock:
                    pass
    """, relpath="ray_tpu/_private/planes.py", root=tmp_path,
        rules=["LCK002"])
    assert findings == []


def test_lck002_out_of_scope_paths_are_ignored(tmp_path):
    # LCK002 scopes to the control/weight/ckpt/serve planes
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        import threading

        class T:
            def __init__(self):
                self._lock = threading.Lock()

            def a(self):
                with self._lock:
                    self.b()

            def b(self):
                with self._lock:
                    pass
    """, relpath="ray_tpu/data/loader.py", root=tmp_path, rules=["LCK002"])
    assert findings == []


def test_lck002_suppression(tmp_path):
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()

            def put(self):
                with self._lock:
                    # interprocedural edges anchor at the call site that
                    # nests the acquisition, so the excuse lives there
                    self._evict()  # raylint: disable=LCK002 _evict drops the lock first on this path

            def _evict(self):
                with self._lock:
                    pass
    """, relpath="ray_tpu/_private/store_mod.py", root=tmp_path,
        rules=["LCK002"])
    assert findings == []


# ---------------------------------------------------------------------------
# AWT002 — await while holding a lock (flow-sensitive)
# ---------------------------------------------------------------------------


def test_awt002_positive_acquire_then_await(tmp_path):
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        import asyncio

        class S:
            async def step(self):
                self._lock.acquire()
                await asyncio.sleep(0)
                self._lock.release()
    """, relpath="ray_tpu/_private/svc.py", root=tmp_path, rules=["AWT002"])
    assert rules_of(findings) == ["AWT002"]
    assert "_lock" in findings[0].message


def test_awt002_positive_held_only_on_one_branch(tmp_path):
    # flow-sensitivity: the lock is held at the await only on the
    # if-branch; a may-analysis must still flag it
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        import asyncio

        class S:
            async def step(self, fast):
                if not fast:
                    self._lock.acquire()
                await asyncio.sleep(0)
    """, relpath="ray_tpu/_private/svc.py", root=tmp_path, rules=["AWT002"])
    assert rules_of(findings) == ["AWT002"]


def test_awt002_positive_helper_leaves_lock_held(tmp_path):
    # one level of call inlining: the helper acquires and never releases
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        import asyncio

        class S:
            def _grab(self):
                self._lock.acquire()

            async def step(self):
                self._grab()
                await asyncio.sleep(0)
                self._lock.release()
    """, relpath="ray_tpu/_private/svc.py", root=tmp_path, rules=["AWT002"])
    assert rules_of(findings) == ["AWT002"]


def test_awt002_positive_alias_resolved_by_reaching_defs(tmp_path):
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        import asyncio

        class S:
            async def step(self):
                lk = self._lock
                lk.acquire()
                await asyncio.sleep(0)
                lk.release()
    """, relpath="ray_tpu/_private/svc.py", root=tmp_path, rules=["AWT002"])
    assert rules_of(findings) == ["AWT002"]


def test_awt002_negative_released_before_await(tmp_path):
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        import asyncio

        class S:
            def _grab(self):
                self._lock.acquire()

            def _drop(self):
                self._lock.release()

            async def ok_one(self):
                self._lock.acquire()
                self._lock.release()
                await asyncio.sleep(0)

            async def ok_two(self):
                self._grab()
                self._drop()
                await asyncio.sleep(0)

            async def ok_async_lock(self):
                # an AWAITED acquire is an asyncio lock: fine by this rule
                await self._aio_lock.acquire()
                await asyncio.sleep(0)
    """, relpath="ray_tpu/_private/svc.py", root=tmp_path, rules=["AWT002"])
    assert findings == []


def test_awt002_suppression(tmp_path):
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        import asyncio

        class S:
            async def step(self):
                self._lock.acquire()
                await asyncio.sleep(0)  # raylint: disable=AWT002 single-threaded test shim; nothing else takes this lock
                self._lock.release()
    """, relpath="ray_tpu/_private/svc.py", root=tmp_path, rules=["AWT002"])
    assert findings == []


# ---------------------------------------------------------------------------
# WIRE002 — wire-schema drift
# ---------------------------------------------------------------------------


def test_wire002_missing_handler(tmp_path):
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        async def ask(client):
            return await client.call("NoSuchMethod", b"")
    """, relpath="ray_tpu/_private/clientside.py", root=tmp_path,
        rules=["WIRE002"])
    assert rules_of(findings) == ["WIRE002"]
    assert "NoSuchMethod" in findings[0].message
    assert "no server" in findings[0].message


def test_wire002_orphan_handler(tmp_path):
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        class Gcs:
            async def _rpc_NeverCalled(self, req, conn):
                return {}
    """, relpath="ray_tpu/_private/serverside.py", root=tmp_path,
        rules=["WIRE002"])
    assert rules_of(findings) == ["WIRE002"]
    assert "NeverCalled" in findings[0].message
    assert "no client call site" in findings[0].message


def test_wire002_negative_both_sides_present(tmp_path):
    # handler in one module, caller in another: parity is whole-program
    pkg = tmp_path / "ray_tpu" / "_private"
    pkg.mkdir(parents=True)
    (pkg / "serverside.py").write_text(textwrap.dedent("""
        class Gcs:
            async def _rpc_Heartbeat(self, req, conn):
                return {}

            async def _handle(self, method, payload, conn):
                if method == "FastPath":
                    return b""
    """))
    findings = lint("""
        async def beat(client):
            await client.call("Heartbeat", b"")
            await client.notify("FastPath", b"")
    """, relpath="ray_tpu/_private/clientside.py", root=tmp_path,
        rules=["WIRE002"])
    assert findings == []


def test_wire002_variable_method_and_wrapper_param(tmp_path):
    # a literal bound to a variable, and a literal passed to a wrapper's
    # `method` parameter, both count as call sites (no false orphans)
    pkg = tmp_path / "ray_tpu" / "_private"
    pkg.mkdir(parents=True)
    (pkg / "serverside.py").write_text(textwrap.dedent("""
        class W:
            async def _rpc_ProfileA(self, req, conn):
                return {}

            async def _rpc_ProfileB(self, req, conn):
                return {}

            async def _rpc_Announce(self, req, conn):
                return {}
    """))
    findings = lint("""
        class R:
            async def _notify_owner(self, owner, method, payload):
                pass

            async def go(self, client, kind):
                method = "ProfileA" if kind == "a" else "ProfileB"
                await client.call(method, b"")
                await self._notify_owner("o", "Announce", {})
    """, relpath="ray_tpu/_private/clientside.py", root=tmp_path,
        rules=["WIRE002"])
    assert findings == []


def test_wire002_registry_field_drift(tmp_path):
    # decode reads a field that is not encoded -> KeyError on every message
    pkg = tmp_path / "ray_tpu" / "_private"
    pkg.mkdir(parents=True)
    (pkg / "common.py").write_text(textwrap.dedent("""
        from dataclasses import dataclass

        @dataclass
        class Spec:
            a: int = 0
            b: int = 0
    """))
    findings = lint("""
        from ray_tpu._private.common import Spec

        def register_struct(cls, fields=None, decode=None):
            return cls

        register_struct(Spec, fields=("a",),
                        decode=lambda f: Spec(f["a"], f["b"]))
    """, relpath="ray_tpu/_private/wire.py", root=tmp_path,
        rules=["WIRE002"])
    assert rules_of(findings) == ["WIRE002"]
    assert "`b`" in findings[0].message and "KeyError" in findings[0].message


def test_wire002_registry_dropped_and_unknown_fields(tmp_path):
    pkg = tmp_path / "ray_tpu" / "_private"
    pkg.mkdir(parents=True)
    (pkg / "common.py").write_text(textwrap.dedent("""
        class Spec:
            def __init__(self, a):
                self.a = a
    """))
    findings = lint("""
        from ray_tpu._private.common import Spec

        def register_struct(cls, fields=None, decode=None):
            return cls

        register_struct(Spec, fields=("a", "ghost"),
                        decode=lambda f: Spec(f["a"]))
    """, relpath="ray_tpu/_private/wire.py", root=tmp_path,
        rules=["WIRE002"])
    msgs = " | ".join(f.message for f in findings)
    # "ghost" is both dropped-by-decode and absent from the struct
    assert "silently dropped" in msgs
    assert "no field or constructor parameter `ghost`" in msgs


def test_wire002_registry_negative_exact_parity(tmp_path):
    pkg = tmp_path / "ray_tpu" / "_private"
    pkg.mkdir(parents=True)
    (pkg / "common.py").write_text(textwrap.dedent("""
        from dataclasses import dataclass

        @dataclass
        class Spec:
            a: int = 0
            b: int = 0
    """))
    findings = lint("""
        from ray_tpu._private.common import Spec

        def register_struct(cls, fields=None, decode=None):
            return cls

        register_struct(Spec, fields=("a", "b"),
                        decode=lambda f: Spec(f["a"], f["b"]))
        register_struct(Spec)  # dataclass-default fields: definitionally in sync
    """, relpath="ray_tpu/_private/wire.py", root=tmp_path,
        rules=["WIRE002"])
    assert findings == []


def test_wire002_suppression(tmp_path):
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        class Gcs:
            # raylint: disable=WIRE002 debug surface for external tooling
            async def _rpc_DebugDump(self, req, conn):
                return {}
    """, relpath="ray_tpu/_private/serverside.py", root=tmp_path,
        rules=["WIRE002"])
    assert findings == []


# ---------------------------------------------------------------------------
# SUP001 — stale suppressions
# ---------------------------------------------------------------------------


def test_sup001_stale_directive_is_an_error():
    findings = lint("""
        import time

        def sync_fn():
            time.sleep(1)  # raylint: disable=ASY001 not async at all
    """, rules=["ASY001", "SUP001"])
    assert rules_of(findings) == ["SUP001"]
    assert "disable=ASY001" in findings[0].message


def test_sup001_used_directive_is_fine():
    findings = lint("""
        import time

        async def f():
            time.sleep(1)  # raylint: disable=ASY001 reviewed: measured dwell is 2us
    """, rules=["ASY001", "SUP001"])
    assert findings == []


def test_sup001_escape_hatch_keeps_dormant_directive():
    findings = lint("""
        import time

        def sync_fn():
            # raylint: disable=ASY001,SUP001 becomes async again in the MPMD refactor; keep the fence
            time.sleep(1)
    """, rules=["ASY001", "SUP001"])
    assert findings == []


def test_sup001_mixed_directive_flags_only_the_dead_token():
    findings = lint("""
        import time
        import pickle

        async def f(blob):
            time.sleep(1)  # raylint: disable=ASY001,SER001 hot path
            return blob
    """, rules=["ASY001", "SER001", "SUP001"])
    assert rules_of(findings) == ["SUP001"]
    assert "disable=SER001" in findings[0].message


def test_sup001_subset_runs_do_not_false_flag():
    # judging an ASY001 directive requires ASY001 to have run
    findings = lint("""
        import time

        def sync_fn():
            time.sleep(1)  # raylint: disable=ASY001 not async
    """, rules=["SER001", "SUP001"])
    assert findings == []


def test_sup001_stale_filewide_directive():
    findings = lint("""
        # raylint: disable-file=TRC001
        x = 1
    """, rules=["TRC001", "SUP001"])
    assert rules_of(findings) == ["SUP001"]


# ---------------------------------------------------------------------------
# OBS001 — metric naming + static span names
# ---------------------------------------------------------------------------


def test_obs001_positive_unprefixed_and_undescribed_metric():
    findings = lint("""
        from ray_tpu.util.metrics import Counter, Gauge, Histogram

        hits = Counter("cache_hits", "cache hit count")
        depth = Gauge("ray_tpu_queue_depth")
        lat = Histogram("ray_tpu.rpc.latency_seconds", "")
    """, rules=["OBS001"])
    assert rules_of(findings) == ["OBS001"] * 3
    assert "ray_tpu_" in findings[0].message       # missing prefix
    assert "description" in findings[1].message    # missing description
    assert "description" in findings[2].message    # empty description


def test_obs001_positive_dynamic_metric_name_and_fstring_span():
    findings = lint("""
        from ray_tpu.util import tracing
        from ray_tpu.util.metrics import Counter

        def make(name, request_id):
            c = Counter(f"ray_tpu_{name}", "per-thing counter")
            with tracing.profile(f"handle:{request_id}"):
                pass
            with tracing.profile("handle", request=request_id):
                pass
    """, rules=["OBS001"])
    assert rules_of(findings) == ["OBS001"] * 2
    assert "static string" in findings[0].message
    assert "cardinality" in findings[1].message


def test_obs001_negative_clean_instruments():
    findings = lint("""
        import collections
        from ray_tpu.util import tracing
        from ray_tpu.util.metrics import Counter, Histogram

        c = Counter("ray_tpu_worker_pool_hits", "warm-pool adoption hits")
        h = Histogram("ray_tpu.train.step_seconds", "train step wall time",
                      boundaries=[0.01, 0.1, 1])
        freq = collections.Counter("not a metric at all")

        def f(store):
            with tracing.profile("weights.pull", category="weights",
                                 store=store):
                pass
    """, rules=["OBS001"])
    assert findings == []


def test_obs001_scope_and_suppression():
    # outside ray_tpu/ the rule stands down (tools, tests, benches)
    findings = lint("""
        from ray_tpu.util.metrics import Counter

        c = Counter("bench_probe", "")
    """, relpath="tools/bench_obs.py", rules=["OBS001"])
    assert findings == []
    # a reasoned suppression holds
    findings = lint("""
        from ray_tpu.util.metrics import Counter

        c = Counter("legacy_name", "kept for dashboard compat")  # raylint: disable=OBS001 grandfathered series name
    """, rules=["OBS001"])
    assert findings == []


# ---------------------------------------------------------------------------
# RSH001 — reshard plans proven no-gather before transport lowering
# ---------------------------------------------------------------------------


def test_rsh001_positive_lowering_without_assert():
    findings = lint("""
        from ray_tpu.weights import collective_reshard, plan_reshard

        def reshard(group, host, shards, src, dst):
            plan = plan_reshard(src, dst)
            return collective_reshard(plan, group, host, shards)
    """, relpath="ray_tpu/rl/sync.py", rules=["RSH001"])
    assert rules_of(findings) == ["RSH001"]
    assert "no_gather" in findings[0].message


def test_rsh001_positive_restore_plan_into_lowering():
    findings = lint("""
        from ray_tpu.ckpt.restore import restore_plan
        from ray_tpu.weights.plan import lower_collective

        def program_for(manifest, dst_spec):
            p = restore_plan(manifest, dst_spec)
            return lower_collective(p, inflight_limit_bytes=1 << 20)
    """, relpath="ray_tpu/ckpt/foo.py", rules=["RSH001"])
    assert rules_of(findings) == ["RSH001"]


def test_rsh001_negative_asserted_before_lowering():
    findings = lint("""
        from ray_tpu.weights import collective_reshard, plan_reshard

        def reshard(group, host, shards, src, dst):
            plan = plan_reshard(src, dst)
            assert plan.no_gather(), "gathering reshard rejected"
            return collective_reshard(plan, group, host, shards)

        def reshard_guarded(group, host, shards, src, dst):
            plan = plan_reshard(src, dst)
            if not plan.no_gather():
                raise ValueError("refusing gather")
            return collective_reshard(plan, group, host, shards)
    """, relpath="ray_tpu/rl/sync.py", rules=["RSH001"])
    assert findings == []


def test_rsh001_negative_plan_from_param_and_scope():
    # a plan arriving as a parameter is the callee's contract to verify
    # (transport.collective_reshard lowers with the internal assert);
    # and outside ray_tpu/ the rule stands down
    findings = lint("""
        from ray_tpu.weights import redistribute

        def run(program, plan, group, host, shards):
            return redistribute(program, group, host, shards)
    """, relpath="ray_tpu/weights/helper.py", rules=["RSH001"])
    assert findings == []
    findings = lint("""
        from ray_tpu.weights import collective_reshard, plan_reshard

        def bench(group, host, shards, src, dst):
            plan = plan_reshard(src, dst)
            return collective_reshard(plan, group, host, shards)
    """, relpath="tools/bench_weights.py", rules=["RSH001"])
    assert findings == []


def test_rsh001_suppression():
    findings = lint("""
        from ray_tpu.weights import collective_reshard, plan_reshard

        def broadcast(group, host, shards, src, dst):
            plan = plan_reshard(src, dst)
            return collective_reshard(plan, group, host, shards)  # raylint: disable=RSH001 declared broadcast: dst replicates every leaf
    """, relpath="ray_tpu/rl/sync.py", rules=["RSH001"])
    assert findings == []


# ---------------------------------------------------------------------------
# OBS001 — PR 12 bucket-collective instruments (train.allreduce/bucket
# metrics + per-bucket span names stay static and described)
# ---------------------------------------------------------------------------


def test_obs001_bucket_metrics_positive():
    findings = lint("""
        from ray_tpu.util import tracing
        from ray_tpu.util.metrics import Counter, Histogram

        ar = Histogram("ray_tpu.train.allreduce_seconds")
        bk = Counter("buckets_reduced", "grad buckets reduced")

        def reduce_bucket(idx):
            with tracing.profile(f"train.bucket_allreduce.{idx}"):
                pass
    """, rules=["OBS001"])
    assert rules_of(findings) == ["OBS001"] * 3
    assert "description" in findings[0].message   # undescribed histogram
    assert "ray_tpu_" in findings[1].message      # unprefixed counter
    assert "static string" in findings[2].message  # per-bucket span name


def test_obs001_bucket_metrics_negative_pr12_shapes():
    # the shapes PR 12 actually ships: described ray_tpu.train.* metrics,
    # static span names with the bucket index as a TAG (bounded
    # cardinality lives in attributes, not the name)
    findings = lint("""
        from ray_tpu.util import tracing
        from ray_tpu.util.metrics import Counter, Histogram

        ar = Histogram("ray_tpu.train.allreduce_seconds",
                       "wall time of one grad-bucket collective",
                       boundaries=[0.001, 0.01, 0.1])
        bb = Histogram("ray_tpu.train.bucket_bytes",
                       "payload bytes of one grad bucket",
                       boundaries=[1024, 1 << 20])
        n = Counter("ray_tpu.train.buckets_reduced",
                    "grad buckets reduced through the async path")

        def reduce_bucket(idx, nbytes):
            with tracing.profile("train.bucket_allreduce", category="train",
                                 bucket=idx, nbytes=nbytes):
                pass
            with tracing.profile("pipe.bucket_apply", category="pipe",
                                 bucket=idx):
                pass
    """, rules=["OBS001"])
    assert findings == []


# ---------------------------------------------------------------------------
# OBS001 — PR 14 quantized-comms instruments (quant bytes/encode metrics
# stay prefixed + described; codec/wire facts ride span TAGS, not names)
# ---------------------------------------------------------------------------


def test_obs001_quant_metrics_positive():
    findings = lint("""
        from ray_tpu.util import tracing
        from ray_tpu.util.metrics import Counter, Histogram

        saved = Counter("quant_bytes_saved", "wire bytes saved")
        enc = Histogram("ray_tpu.train.quant_encode_seconds")

        def reduce_quantized(codec):
            with tracing.profile(f"train.bucket_allreduce.{codec}"):
                pass
    """, rules=["OBS001"])
    assert rules_of(findings) == ["OBS001"] * 3
    assert "ray_tpu_" in findings[0].message      # unprefixed counter
    assert "description" in findings[1].message   # undescribed histogram
    assert "static string" in findings[2].message  # codec in the span name


def test_obs001_quant_metrics_negative_pr14_shapes():
    # the shapes the quantized tier actually ships: described
    # ray_tpu.train.quant_* instruments, codec + wire bytes as span tags
    findings = lint("""
        from ray_tpu.util import tracing
        from ray_tpu.util.metrics import Counter, Histogram

        saved = Counter("ray_tpu.train.quant_bytes_saved",
                        "wire bytes saved by the quantized collective "
                        "tier vs fp32")
        enc = Histogram("ray_tpu.train.quant_encode_seconds",
                        "encode/decode CPU time of one quantized payload",
                        boundaries=[0.0001, 0.001, 0.01])

        def reduce_quantized(idx, codec, nbytes):
            with tracing.profile("train.bucket_allreduce", category="train",
                                 bucket=idx, compression=codec,
                                 wire_bytes=nbytes):
                pass
    """, rules=["OBS001"])
    assert findings == []


# ---------------------------------------------------------------------------
# OBS001 — PR 20 channel fast-path instruments (pipe send/recv/encode
# metrics stay prefixed + described; stage, wire bytes, and per-hop
# timings ride span TAGS — never the metric or span name)
# ---------------------------------------------------------------------------


def test_obs001_pipe_channel_metrics_positive():
    findings = lint("""
        from ray_tpu.util import tracing
        from ray_tpu.util.metrics import Counter, Histogram

        snd = Histogram("pipe_send_seconds", "channel send wall time")
        rcv = Histogram("ray_tpu.pipe.recv_wait_seconds")

        def send_hop(stage, mb):
            with tracing.profile(f"pipe.send.{stage}.{mb}"):
                pass
    """, rules=["OBS001"])
    assert rules_of(findings) == ["OBS001"] * 3
    assert "ray_tpu_" in findings[0].message       # unprefixed histogram
    assert "description" in findings[1].message    # undescribed histogram
    assert "static string" in findings[2].message  # stage/mb in span name


def test_obs001_pipe_channel_metrics_negative_pr20_shapes():
    # the shapes the channel fast path actually ships: described
    # ray_tpu.pipe.* instruments tagged by stage, static pipe.send /
    # pipe.recv span names with the hop breakdown riding tags
    findings = lint("""
        from ray_tpu.util import tracing
        from ray_tpu.util.metrics import Counter, Histogram

        snd = Histogram("ray_tpu.pipe.send_seconds",
                        "per-step channel send wall time on one rank",
                        boundaries=[0.001, 0.01, 0.1],
                        tag_keys=("stage",))
        rcv = Histogram("ray_tpu.pipe.recv_wait_seconds",
                        "per-step wait on upstream channel values",
                        boundaries=[0.001, 0.01, 0.1],
                        tag_keys=("stage",))
        enc = Histogram("ray_tpu.pipe.encode_seconds",
                        "zero-copy frame encode time (extract + skeleton)",
                        boundaries=[0.0001, 0.001, 0.01],
                        tag_keys=("stage",))
        wb = Counter("ray_tpu.pipe.wire_bytes",
                     "activation/grad bytes written to channel rings",
                     tag_keys=("stage",))

        def send_hop(stage, mb, nbytes, encode_s, ack_wait_s):
            with tracing.profile("pipe.send", category="pipe", stage=stage,
                                 mb=mb, wire_bytes=nbytes,
                                 encode_s=encode_s, ack_wait_s=ack_wait_s):
                pass
            with tracing.profile("pipe.recv", category="pipe", stage=stage,
                                 mb=mb, wire_bytes=nbytes):
                pass
    """, rules=["OBS001"])
    assert findings == []


# ---------------------------------------------------------------------------
# OBS001 — PR 17 serve autoscale-plane instruments (arrival-rate/queue-depth
# gauges, shed + prefix-cache counters stay prefixed + described; the
# deployment name rides TAGS, never the metric or span name)
# ---------------------------------------------------------------------------


def test_obs001_serve_metrics_positive():
    findings = lint("""
        from ray_tpu.util import tracing
        from ray_tpu.util.metrics import Counter, Gauge

        rate = Gauge("serve_arrival_rate", "windowed arrival rate")
        shed = Counter("ray_tpu.serve.shed_requests")

        def autoscale_tick(deployment):
            with tracing.profile(f"serve.autoscale.{deployment}"):
                pass
    """, rules=["OBS001"])
    assert rules_of(findings) == ["OBS001"] * 3
    assert "ray_tpu_" in findings[0].message      # unprefixed gauge
    assert "description" in findings[1].message   # undescribed counter
    assert "static string" in findings[2].message  # deployment in span name


def test_obs001_serve_metrics_negative_pr17_shapes():
    # the shapes the serve plane actually ships: described
    # ray_tpu.serve.* instruments, deployment/reason as tags
    findings = lint("""
        from ray_tpu.util import tracing
        from ray_tpu.util.metrics import Counter, Gauge

        rate = Gauge("ray_tpu.serve.arrival_rate",
                     "windowed request arrival rate per deployment (req/s)")
        depth = Gauge("ray_tpu.serve.queue_depth",
                      "requests waiting in the ingress fair queue")
        shed = Counter("ray_tpu.serve.shed_requests",
                       "requests rejected by SLO admission control")
        hits = Counter("ray_tpu.serve.prefix_cache_hits",
                       "prefix-routed requests that stayed on the replica "
                       "owning their prompt prefix")

        def autoscale_tick(deployment, direction):
            with tracing.profile("serve.autoscale", category="serve",
                                 deployment=deployment, direction=direction):
                pass
    """, rules=["OBS001"])
    assert findings == []


# ---------------------------------------------------------------------------
# RCE001 — shared-state race across disjoint execution contexts
# ---------------------------------------------------------------------------


def test_rce001_positive_thread_vs_loop_writers(tmp_path):
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        import threading

        class Pump:
            def __init__(self):
                self.pending = 0

            def start(self):
                threading.Thread(target=self._worker).start()

            def _worker(self):
                self.pending = self.pending + 1

            async def drain(self):
                self.pending = 0
    """, relpath="ray_tpu/_private/svc.py", root=tmp_path, rules=["RCE001"])
    assert rules_of(findings) == ["RCE001"]
    assert "Pump.pending" in findings[0].message
    # both sites named with their context sets
    assert "thread" in findings[0].message
    assert "loop" in findings[0].message


def test_rce001_positive_single_site_lazy_init(tmp_path):
    # the task_events._enabled shape: ONE unlocked check-then-act write
    # whose function is reachable from a background thread and the loop —
    # the site races with itself
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        import threading

        _cache = None

        def get_cache():
            global _cache
            if _cache is None:
                _cache = {}
            return _cache

        def start():
            threading.Thread(target=_bg).start()

        def _bg():
            get_cache()

        async def tick():
            get_cache()
    """, relpath="ray_tpu/_private/svc.py", root=tmp_path, rules=["RCE001"])
    assert rules_of(findings) == ["RCE001"]
    assert "_cache" in findings[0].message
    assert "single site" in findings[0].message


def test_rce001_negative_common_lock(tmp_path):
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        import threading

        class Pump:
            def __init__(self):
                self._lock = threading.Lock()
                self.pending = 0

            def start(self):
                threading.Thread(target=self._worker).start()

            def _worker(self):
                with self._lock:
                    self.pending = self.pending + 1

            async def drain(self):
                with self._lock:
                    self.pending = 0
    """, relpath="ray_tpu/_private/svc.py", root=tmp_path, rules=["RCE001"])
    assert findings == []


def test_rce001_negative_overlapping_contexts(tmp_path):
    # two unlocked write sites, but both run in caller ("main") context:
    # no provably disjoint pair, so the disjointness gate keeps it silent
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        class Counter:
            def bump(self):
                self.n = 1

            def reset(self):
                self.n = 0
    """, relpath="ray_tpu/_private/svc.py", root=tmp_path, rules=["RCE001"])
    assert findings == []


def test_rce001_negative_single_site_double_checked_lock(tmp_path):
    # the sanctioned fix for the lazy-init shape: the write under the lock
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        import threading

        _init_lock = threading.Lock()
        _cache = None

        def get_cache():
            global _cache
            if _cache is None:
                with _init_lock:
                    if _cache is None:
                        _cache = {}
            return _cache

        def start():
            threading.Thread(target=_bg).start()

        def _bg():
            get_cache()

        async def tick():
            get_cache()
    """, relpath="ray_tpu/_private/svc.py", root=tmp_path, rules=["RCE001"])
    assert findings == []


def test_rce001_suppression(tmp_path):
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        import threading

        class Pump:
            def start(self):
                threading.Thread(target=self._worker).start()

            def _worker(self):
                self.pending = self.pending + 1

            async def drain(self):
                # raylint: disable=RCE001 benign diagnostic counter, torn values tolerated
                self.pending = 0
    """, relpath="ray_tpu/_private/svc.py", root=tmp_path, rules=["RCE001"])
    assert findings == []


# ---------------------------------------------------------------------------
# RCE002 — loop-read x thread-write advisory
# ---------------------------------------------------------------------------


def test_rce002_positive_loop_read_thread_write(tmp_path):
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        import threading

        class Pipe:
            def start(self):
                threading.Thread(target=self._run).start()

            def _run(self):
                self.closed = True

            async def poll(self):
                return self.closed
    """, relpath="ray_tpu/_private/svc.py", root=tmp_path, rules=["RCE002"])
    assert rules_of(findings) == ["RCE002"]
    assert "Pipe.closed" in findings[0].message
    # anchored at the thread-side write
    assert "self.closed = True" in findings[0].snippet


def test_rce002_negative_deque_handoff_idiom(tmp_path):
    # the sanctioned single-bytecode handoff: thread appends, loop pops
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        import threading
        from collections import deque

        class Pipe:
            def __init__(self):
                self.q = deque()

            def start(self):
                threading.Thread(target=self._run).start()

            def _run(self):
                self.q.append(1)

            async def poll(self):
                if self.q:
                    return self.q.popleft()
    """, relpath="ray_tpu/_private/svc.py", root=tmp_path, rules=["RCE002"])
    assert findings == []


def test_rce002_negative_locked_write(tmp_path):
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        import threading

        class Pipe:
            def __init__(self):
                self._init_lock = threading.Lock()

            def start(self):
                threading.Thread(target=self._run).start()

            def _run(self):
                with self._lock:
                    self.closed = True

            async def poll(self):
                return self.closed
    """, relpath="ray_tpu/_private/svc.py", root=tmp_path, rules=["RCE002"])
    assert findings == []


def test_rce002_suppression(tmp_path):
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        import threading

        class Pipe:
            def start(self):
                threading.Thread(target=self._run).start()

            def _run(self):
                # raylint: disable=RCE002 monotonic flag, stale read only delays shutdown one tick
                self.closed = True

            async def poll(self):
                return self.closed
    """, relpath="ray_tpu/_private/svc.py", root=tmp_path, rules=["RCE002"])
    assert findings == []


# ---------------------------------------------------------------------------
# FRK001 — fork-safety gate
# ---------------------------------------------------------------------------


def test_frk001_positive_zygote_inherited_buffer(tmp_path):
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        _events = []

        def _child_main():
            serve()

        def serve():
            _events.append(1)
    """, relpath="ray_tpu/_private/boot.py", root=tmp_path, rules=["FRK001"])
    assert rules_of(findings) == ["FRK001"]
    assert "`_events`" in findings[0].message
    # the provenance chain names how fork-child context reaches the state
    assert "_child_main" in findings[0].message
    # anchored at the module-state definition, not the use site
    assert findings[0].snippet == "_events = []"


def test_frk001_negative_fork_reachable_reset_hook(tmp_path):
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        _events = []

        def _child_main():
            reset_after_fork()
            serve()

        def reset_after_fork():
            _events.clear()

        def serve():
            _events.append(1)
    """, relpath="ray_tpu/_private/boot.py", root=tmp_path, rules=["FRK001"])
    assert findings == []


def test_frk001_positive_lock_held_across_fork(tmp_path):
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        import os
        import threading

        _state_lock = threading.Lock()

        def spawn():
            with _state_lock:
                pid = os.fork()
            return pid
    """, relpath="ray_tpu/_private/boot.py", root=tmp_path, rules=["FRK001"])
    assert rules_of(findings) == ["FRK001"]
    assert "os.fork() while holding" in findings[0].message


def test_frk001_positive_call_into_fork_path_while_locked(tmp_path):
    # the lock is released before THIS function's own fork... but the
    # caller holds one across a call that transitively reaches os.fork()
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        import os
        import threading

        _state_lock = threading.Lock()

        def outer():
            with _state_lock:
                return spawn()

        def spawn():
            return os.fork()
    """, relpath="ray_tpu/_private/boot.py", root=tmp_path, rules=["FRK001"])
    assert rules_of(findings) == ["FRK001"]
    assert "fork path `spawn`" in findings[0].message


def test_frk001_suppression(tmp_path):
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        # raylint: disable=FRK001 append-only registry, identical in parent and child
        _events = []

        def _child_main():
            serve()

        def serve():
            _events.append(1)
    """, relpath="ray_tpu/_private/boot.py", root=tmp_path, rules=["FRK001"])
    assert findings == []


# ---------------------------------------------------------------------------
# DON001 — use-after-donate in the jit planes
# ---------------------------------------------------------------------------


def test_don001_positive_read_after_donating_call(tmp_path):
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        import jax

        def train(params, grads, update):
            step = jax.jit(update, donate_argnums=(0,))
            new_params = step(params, grads)
            norm = params
            return new_params, norm
    """, relpath="ray_tpu/parallel/mod.py", root=tmp_path, rules=["DON001"])
    assert rules_of(findings) == ["DON001"]
    assert "`params` was donated" in findings[0].message
    assert findings[0].snippet == "norm = params"


def test_don001_positive_read_on_one_branch_only(tmp_path):
    # forward-MAY analysis: a read on any path after the donation fires,
    # even when the other branch never touches the buffer
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        import jax

        def train(params, grads, update, debug, log):
            step = jax.jit(update, donate_argnums=(0,))
            out = step(params, grads)
            if debug:
                log(params)
            return out
    """, relpath="ray_tpu/parallel/mod.py", root=tmp_path, rules=["DON001"])
    assert rules_of(findings) == ["DON001"]
    assert "log(params)" in findings[0].snippet


def test_don001_negative_rebind_kills_the_fact(tmp_path):
    # the sanctioned donation idiom: read before, rebind from the result
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        import jax

        def train(params, grads, update):
            step = jax.jit(update, donate_argnums=(0,))
            norm = params
            params = step(params, grads)
            return params, norm
    """, relpath="ray_tpu/parallel/mod.py", root=tmp_path, rules=["DON001"])
    assert findings == []


def test_don001_decorated_partial_donate_argnames(tmp_path):
    # @partial(jax.jit, donate_argnames=...) resolves names to positions
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        from functools import partial

        import jax

        @partial(jax.jit, donate_argnames=("state",))
        def update(state, batch):
            return state

        def drive(state, batch):
            new = update(state, batch)
            return state
    """, relpath="ray_tpu/parallel/mod.py", root=tmp_path, rules=["DON001"])
    assert rules_of(findings) == ["DON001"]
    assert findings[0].snippet == "return state"


def test_don001_conditional_argnums_fold_to_may_donate(tmp_path):
    # (0,) if donate else None folds to the UNION: may-donate -> finding
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        import jax

        def train(params, grads, update, donate):
            step = jax.jit(update, donate_argnums=(0,) if donate else None)
            out = step(params, grads)
            return params
    """, relpath="ray_tpu/parallel/mod.py", root=tmp_path, rules=["DON001"])
    assert rules_of(findings) == ["DON001"]


def test_don001_out_of_scope_module_is_silent(tmp_path):
    # same source outside the jit planes: not DON001's business
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        import jax

        def train(params, grads, update):
            step = jax.jit(update, donate_argnums=(0,))
            new_params = step(params, grads)
            norm = params
            return new_params, norm
    """, relpath="ray_tpu/_private/mod.py", root=tmp_path, rules=["DON001"])
    assert findings == []


def test_don001_suppression(tmp_path):
    (tmp_path / "ray_tpu").mkdir(parents=True)
    findings = lint("""
        import jax

        def train(params, grads, update):
            step = jax.jit(update, donate_argnums=(0,))
            new_params = step(params, grads)
            norm = params  # raylint: disable=DON001 host-side numpy mirror, not a device buffer
            return new_params, norm
    """, relpath="ray_tpu/parallel/mod.py", root=tmp_path, rules=["DON001"])
    assert findings == []


# ---------------------------------------------------------------------------
# OBS001 — goodput ledger + serve TTFT instruments (this PR's
# ray_tpu.goodput.* / ray_tpu.serve.ttft_* series stay prefixed +
# described; bucket names ride TAGS, never the metric name)
# ---------------------------------------------------------------------------


def test_obs001_goodput_metrics_positive():
    findings = lint("""
        from ray_tpu.util.metrics import Gauge, Histogram

        frac = Gauge("goodput.fraction", "step_compute share of wall")
        mfu = Gauge("ray_tpu.goodput.mfu")

        def per_bucket(bucket):
            return Gauge("ray_tpu.goodput." + bucket + "_seconds",
                         "per-bucket seconds")
    """, rules=["OBS001"])
    assert rules_of(findings) == ["OBS001"] * 3
    assert "ray_tpu_" in findings[0].message      # unprefixed gauge
    assert "description" in findings[1].message   # undescribed MFU gauge
    assert "static string" in findings[2].message  # per-bucket metric NAME


def test_obs001_goodput_metrics_negative_shipped_shapes():
    # the shapes this PR actually ships: every goodput/TTFT series is
    # prefixed + described, the bucket axis is a tag on ONE gauge
    findings = lint("""
        from ray_tpu.util.metrics import Gauge, Histogram

        frac = Gauge("ray_tpu.goodput.fraction",
                     "step_compute share of ledger wall time for this "
                     "process's active job")
        mfu = Gauge("ray_tpu.goodput.mfu",
                    "model FLOPs utilization last reported by the train "
                    "loop on this process")
        compiles = Gauge("ray_tpu.goodput.compiles",
                         "cumulative jit compiles observed by the "
                         "compile watch")
        recompiles = Gauge("ray_tpu.goodput.recompiles",
                           "cumulative shape/dtype-keyed jit RE-compiles "
                           "(same program, new key)")
        bucket_s = Gauge("ray_tpu.goodput.bucket_seconds",
                         "cumulative attributed wall seconds per goodput "
                         "bucket", tag_keys=("bucket",))
        ttft = Histogram("ray_tpu.serve.ttft_seconds",
                         "server-side time to first response chunk",
                         boundaries=[0.01, 0.1, 1.0])
        ttft_p99 = Gauge("ray_tpu.serve.ttft_p99_seconds",
                         "windowed p99 of replica-stamped TTFT")
    """, rules=["OBS001"])
    assert findings == []

"""Pluggable external spill storage (reference tier:
python/ray/tests/test_object_spilling with custom external storage)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private.config import RAY_CONFIG
from ray_tpu._private.external_storage import (
    ExternalStorage,
    FileSystemStorage,
    setup_external_storage,
)
from ray_tpu._private.object_store import ObjectStoreServer


class CountingStorage(ExternalStorage):
    """Plugin backend: delegates to the filesystem but counts every call —
    proves spill/restore/delete route through the plugin, not open()."""

    calls = {"spill": 0, "restore": 0, "delete": 0}

    def __init__(self, directory):
        self._fs = FileSystemStorage(directory)

    def spill(self, key, data):
        CountingStorage.calls["spill"] += 1
        return "plugin://" + self._fs.spill(key, data)

    def restore(self, uri):
        CountingStorage.calls["restore"] += 1
        return self._fs.restore(uri[len("plugin://"):])

    def delete(self, uri):
        CountingStorage.calls["delete"] += 1
        self._fs.delete(uri[len("plugin://"):])


def test_setup_resolves_specs(tmp_path):
    fs = setup_external_storage("", str(tmp_path))
    assert isinstance(fs, FileSystemStorage)
    fs = setup_external_storage("filesystem", str(tmp_path))
    assert isinstance(fs, FileSystemStorage)
    plugin = setup_external_storage(
        "test_external_storage:CountingStorage", str(tmp_path))
    assert isinstance(plugin, CountingStorage)
    with pytest.raises(ValueError):
        setup_external_storage("not-a-valid-spec", str(tmp_path))


def test_filesystem_roundtrip_and_range(tmp_path):
    fs = FileSystemStorage(str(tmp_path))
    uri = fs.spill("k1", b"0123456789")
    assert fs.restore(uri) == b"0123456789"
    assert fs.restore_range(uri, 3, 4) == b"3456"
    fs.delete(uri)
    fs.delete(uri)  # idempotent


def test_store_spills_through_plugin(tmp_path, monkeypatch):
    monkeypatch.setattr(RAY_CONFIG, "object_spill_storage",
                        "test_external_storage:CountingStorage")
    CountingStorage.calls = {"spill": 0, "restore": 0, "delete": 0}
    store = ObjectStoreServer("feedface" * 4, capacity=1 << 20,
                              spill_dir=str(tmp_path))
    try:
        # fill past capacity: 3 x 512KB into a 1MB store forces LRU spill
        payloads = {}
        for i in range(3):
            oid = bytes([i]) * 28
            data = bytes([65 + i]) * (512 * 1024)
            reply = store.create(oid, len(data), 0)
            assert reply["status"] == "ok", reply
            from ray_tpu._private.object_store import ShmSegment

            if "shm_name" in reply:
                seg = ShmSegment(reply["shm_name"])
                seg.buf[: len(data)] = data
                seg.close()
            else:
                seg = ShmSegment(reply["arena_name"])
                memoryview(seg.buf)[reply["offset"]: reply["offset"]
                                    + len(data)] = data
                seg.close()
            store.seal(oid, 0)
            payloads[oid] = data
        assert CountingStorage.calls["spill"] >= 1
        # every object remains readable (spilled ones restore via plugin)
        for oid, data in payloads.items():
            got = store.read_chunk(oid, 0, len(data))
            assert got[:16] == data[:16]
        assert (CountingStorage.calls["restore"]
                + CountingStorage.calls["spill"]) >= 3
        store.delete(list(payloads))
        assert CountingStorage.calls["delete"] >= 1
    finally:
        store.shutdown()

"""Borrow-protocol chaos: the owner-initiated watch (reference:
WaitForRefRemoved in reference_counter.cc) must survive transient RPC
failures without freeing live borrows, and must reclaim borrows from dead
borrowers (worker death) instead of pinning objects forever.
"""

import gc
import os
import pickle
import time

import numpy as np
import pytest

from ray_tpu._private import wire
import ray_tpu


def _store_objects():
    w = ray_tpu._private.worker.global_worker()
    return wire.loads(w._run(w.raylet.call("StoreStats", b"")))["num_objects"]


def _wait_store_below(n, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if _store_objects() <= n:
            return True
        time.sleep(0.25)
    return False


@pytest.fixture
def chaos_cluster():
    """Fresh cluster with driver-side chaos on the borrow-watch probes."""
    ray_tpu.shutdown()
    os.environ["RAY_TPU_TESTING_RPC_FAILURE"] = "WaitBorrowsDone=2:0"
    try:
        ray_tpu.init(num_cpus=4)
        yield ray_tpu
    finally:
        del os.environ["RAY_TPU_TESTING_RPC_FAILURE"]
        ray_tpu.shutdown()


@pytest.fixture
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield ray_tpu
    ray_tpu.shutdown()


@ray_tpu.remote
class Holder:
    def __init__(self):
        self.box = None

    def stash(self, box):
        self.box = box  # keeps the contained ref: becomes a borrower
        return "ok"

    def read(self):
        return float(ray_tpu.get(self.box[0])[0])

    def drop(self):
        self.box = None
        return "dropped"


def test_watch_survives_transient_probe_failures(chaos_cluster):
    """The first two WaitBorrowsDone probes fail (injected); the owner must
    NOT treat the borrower as dead and free a live borrow."""
    h = Holder.remote()
    ref = ray_tpu.put(np.full(300_000, 5.0))
    assert ray_tpu.get(h.stash.remote([ref]), timeout=60) == "ok"
    del ref
    gc.collect()
    time.sleep(6.0)  # grace + both injected probe failures elapse
    assert ray_tpu.get(h.read.remote(), timeout=60) == 5.0
    # and release still frees once the borrower drops it
    before = _store_objects()
    assert ray_tpu.get(h.drop.remote(), timeout=60) == "dropped"
    assert _wait_store_below(before - 1, timeout=60.0), (
        "object not freed after borrower release (watch wedged by chaos?)")


def test_dead_borrower_reclaimed(cluster):
    """A killed borrower must not pin the object forever: the owner's watch
    detects unreachability and reclaims the borrow."""
    h = Holder.remote()
    before = _store_objects()
    ref = ray_tpu.put(np.full(300_000, 8.0))
    assert ray_tpu.get(h.stash.remote([ref]), timeout=60) == "ok"
    time.sleep(1.0)  # let the borrow register
    del ref
    gc.collect()
    time.sleep(2.0)  # owner-zero + grace pass; borrow alone pins it
    assert _store_objects() >= before + 1
    from ray_tpu._private.config import RAY_CONFIG

    old = RAY_CONFIG.borrower_death_timeout_s
    RAY_CONFIG.borrower_death_timeout_s = 10.0  # keep the test fast
    try:
        ray_tpu.kill(h)  # borrower dies holding the borrow
        assert _wait_store_below(before, timeout=90.0), (
            "dead borrower still pins the object (watch did not reclaim)")
    finally:
        RAY_CONFIG.borrower_death_timeout_s = old

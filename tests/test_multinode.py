"""Multi-node tests via the cluster_utils harness: cross-node objects,
label scheduling, node failure (reference: python/ray/tests with
ray_start_cluster, SURVEY.md §4)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util.scheduling_strategies import NodeLabelSchedulingStrategy


@pytest.fixture(scope="module")
def multinode():
    ray_tpu.shutdown()
    cluster = Cluster(head_node_args={"resources": {"CPU": 2.0}})
    cluster.add_node(resources={"CPU": 2.0, "zone_b": 1.0},
                     labels={"zone": "b"})
    ray_tpu.init(address=cluster.address)
    cluster.wait_for_nodes(2)
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


def test_two_nodes_visible(multinode):
    nodes = [n for n in ray_tpu.nodes() if n["alive"]]
    assert len(nodes) == 2
    assert ray_tpu.cluster_resources()["CPU"] == 4.0


def test_custom_resource_routing(multinode):
    @ray_tpu.remote(resources={"zone_b": 0.1}, num_cpus=0.1)
    def where():
        return ray_tpu.get_runtime_context().get_node_id()

    node_hex = ray_tpu.get(where.remote(), timeout=60)
    labeled = [n for n in ray_tpu.nodes() if n["labels"].get("zone") == "b"]
    assert node_hex == labeled[0]["node_id"]


def test_label_scheduling(multinode):
    @ray_tpu.remote
    def where():
        return ray_tpu.get_runtime_context().get_node_id()

    strat = NodeLabelSchedulingStrategy(hard={"zone": "b"})
    node_hex = ray_tpu.get(
        where.options(scheduling_strategy=strat, num_cpus=0.1).remote(), timeout=60)
    labeled = [n for n in ray_tpu.nodes() if n["labels"].get("zone") == "b"]
    assert node_hex == labeled[0]["node_id"]


def test_cross_node_object_transfer(multinode):
    """A large object produced on node B is pulled chunk-wise to node A."""

    @ray_tpu.remote(resources={"zone_b": 0.1}, num_cpus=0.1)
    def produce():
        return np.full((2048, 1024), 7.0)  # 16 MiB

    @ray_tpu.remote(num_cpus=0.1)
    def consume(arr):
        return float(arr.mean())

    ref = produce.remote()
    # force consumption with affinity away from b is not guaranteed; just
    # validate the value flows regardless of which node consumes it
    assert ray_tpu.get(consume.remote(ref), timeout=120) == 7.0
    assert ray_tpu.get(ref, timeout=120).shape == (2048, 1024)


def test_node_failure_detected(multinode):
    node = multinode.add_node(resources={"CPU": 1.0, "doomed": 1.0})
    multinode.wait_for_nodes(3)
    multinode.remove_node(node)
    deadline = time.time() + 30
    while time.time() < deadline:
        alive = [n for n in ray_tpu.nodes() if n["alive"]]
        if len(alive) == 2:
            return
        time.sleep(0.5)
    raise AssertionError("GCS did not detect node death")

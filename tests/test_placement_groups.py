"""Placement groups + TPU slice gang scheduling on a fake multi-node cluster.

Reference tier: python/ray/tests/test_placement_group*.py; fake TPU slices
via node labels mirror the reference's fake_multi_node testing approach.
"""

import pytest

import ray_tpu
from ray_tpu._private.common import LABEL_TPU_SLICE
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util.placement_group import (
    get_placement_group_state,
    placement_group,
    remove_placement_group,
)
from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy
from ray_tpu.util.tpu import slice_placement_group


@pytest.fixture(scope="module")
def tpu_cluster():
    """Head + 4 fake TPU hosts: 2 on slice-a, 2 on slice-b (4 chips each)."""
    ray_tpu.shutdown()
    cluster = Cluster(head_node_args={"resources": {"CPU": 2.0}})
    for slice_name in ("slice-a", "slice-b"):
        for _ in range(2):
            cluster.add_node(
                resources={"CPU": 4.0, "TPU": 4.0},
                labels={LABEL_TPU_SLICE: slice_name},
            )
    ray_tpu.init(address=cluster.address)
    cluster.wait_for_nodes(5)
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


def test_pg_pack_and_task(tpu_cluster):
    pg = placement_group([{"CPU": 1.0}, {"CPU": 1.0}], strategy="PACK")
    assert pg.ready(timeout=60)

    @ray_tpu.remote(num_cpus=1)
    def where():
        return ray_tpu.get_runtime_context().get_node_id()

    strat = PlacementGroupSchedulingStrategy(pg, placement_group_bundle_index=0)
    node = ray_tpu.get(where.options(scheduling_strategy=strat).remote(), timeout=60)
    assert node in [n["node_id"] for n in ray_tpu.nodes()]
    remove_placement_group(pg)


def test_pg_strict_spread(tpu_cluster):
    pg = placement_group([{"CPU": 1.0}] * 3, strategy="STRICT_SPREAD")
    assert pg.ready(timeout=60)
    nodes = pg.bundle_nodes()
    assert len(set(nodes)) == 3
    remove_placement_group(pg)


def _wait_cpu(predicate, timeout=20.0):
    import time

    deadline = time.time() + timeout
    while time.time() < deadline:
        value = ray_tpu.available_resources().get("CPU", 0)
        if predicate(value):
            return value
        time.sleep(0.3)
    return ray_tpu.available_resources().get("CPU", 0)


def test_pg_resources_returned_on_remove(tpu_cluster):
    before = _wait_cpu(lambda v: v >= 17.9)  # quiesce: 2 + 4*4 minus collective store
    pg = placement_group([{"CPU": 2.0}], strategy="PACK")
    assert pg.ready(timeout=60)
    during = _wait_cpu(lambda v: v <= before - 2.0 + 0.01)
    assert during <= before - 2.0 + 0.01
    remove_placement_group(pg)
    after = _wait_cpu(lambda v: v >= before - 0.01)
    assert after >= before - 0.01


def test_slice_placement_group(tpu_cluster):
    spg = slice_placement_group(num_hosts=2)
    assert spg.ready(timeout=60)
    assert spg.num_chips == 8
    nodes_by_id = {n["node_id"]: n for n in ray_tpu.nodes()}
    bundle_nodes = spg.placement_group.bundle_nodes()
    assert len(set(bundle_nodes)) == 2
    slices = {nodes_by_id[nid]["labels"][LABEL_TPU_SLICE] for nid in bundle_nodes}
    assert len(slices) == 1 and slices.pop() == spg.slice_name

    # gang actors on the slice
    @ray_tpu.remote(num_tpus=4, num_cpus=1)
    class HostWorker:
        def node(self):
            return ray_tpu.get_runtime_context().get_node_id()

    strat0 = PlacementGroupSchedulingStrategy(spg.placement_group, 0)
    strat1 = PlacementGroupSchedulingStrategy(spg.placement_group, 1)
    w0 = HostWorker.options(scheduling_strategy=strat0).remote()
    w1 = HostWorker.options(scheduling_strategy=strat1).remote()
    n0 = ray_tpu.get(w0.node.remote(), timeout=120)
    n1 = ray_tpu.get(w1.node.remote(), timeout=120)
    assert {n0, n1} == set(bundle_nodes)
    remove_placement_group(spg.placement_group)


def test_pg_state_api(tpu_cluster):
    pg = placement_group([{"CPU": 1.0}], strategy="PACK", name="mypg")
    assert pg.ready(timeout=60)
    info = get_placement_group_state(pg)
    assert info["state"] == "CREATED" and info["name"] == "mypg"
    remove_placement_group(pg)

"""Placement groups + TPU slice gang scheduling on a fake multi-node cluster.

Reference tier: python/ray/tests/test_placement_group*.py; fake TPU slices
via node labels mirror the reference's fake_multi_node testing approach.
"""

import pytest

import ray_tpu
from ray_tpu._private.common import LABEL_TPU_SLICE
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util.placement_group import (
    get_placement_group_state,
    placement_group,
    remove_placement_group,
)
from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy
from ray_tpu.util.tpu import slice_placement_group


@pytest.fixture(scope="module")
def tpu_cluster():
    """Head + 4 fake TPU hosts: 2 on slice-a, 2 on slice-b (4 chips each)."""
    ray_tpu.shutdown()
    cluster = Cluster(head_node_args={"resources": {"CPU": 2.0}})
    for slice_name in ("slice-a", "slice-b"):
        for _ in range(2):
            cluster.add_node(
                resources={"CPU": 4.0, "TPU": 4.0},
                labels={LABEL_TPU_SLICE: slice_name},
            )
    ray_tpu.init(address=cluster.address)
    cluster.wait_for_nodes(5)
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


def test_pg_pack_and_task(tpu_cluster):
    pg = placement_group([{"CPU": 1.0}, {"CPU": 1.0}], strategy="PACK")
    assert pg.ready(timeout=60)

    @ray_tpu.remote(num_cpus=1)
    def where():
        return ray_tpu.get_runtime_context().get_node_id()

    strat = PlacementGroupSchedulingStrategy(pg, placement_group_bundle_index=0)
    node = ray_tpu.get(where.options(scheduling_strategy=strat).remote(), timeout=60)
    assert node in [n["node_id"] for n in ray_tpu.nodes()]
    remove_placement_group(pg)


def test_pg_strict_spread(tpu_cluster):
    pg = placement_group([{"CPU": 1.0}] * 3, strategy="STRICT_SPREAD")
    assert pg.ready(timeout=60)
    nodes = pg.bundle_nodes()
    assert len(set(nodes)) == 3
    remove_placement_group(pg)


def _wait_cpu(predicate, timeout=20.0):
    import time

    deadline = time.time() + timeout
    while time.time() < deadline:
        value = ray_tpu.available_resources().get("CPU", 0)
        if predicate(value):
            return value
        time.sleep(0.3)
    return ray_tpu.available_resources().get("CPU", 0)


def test_pg_resources_returned_on_remove(tpu_cluster):
    total = ray_tpu.cluster_resources()["CPU"]  # 2 + 4*4 = 18
    before = _wait_cpu(lambda v: v >= total - 0.1)  # quiesce to full capacity
    assert before <= total + 0.01, (
        f"available CPU {before} exceeds cluster total {total}: a lease or "
        f"bundle release double-credited a node pool")
    pg = placement_group([{"CPU": 2.0}], strategy="PACK")
    assert pg.ready(timeout=60)
    during = _wait_cpu(lambda v: v <= before - 2.0 + 0.01)
    assert during <= before - 2.0 + 0.01, _dump_nodes()
    remove_placement_group(pg)
    after = _wait_cpu(lambda v: v >= before - 0.01)
    assert after >= before - 0.01, _dump_nodes()
    assert after <= total + 0.01, _dump_nodes()


def _dump_nodes():
    """Per-node availability snapshot for accounting-failure diagnostics."""
    try:
        return "; ".join(
            f"{n['node_id'][:8]}: avail={n.get('available')}"
            for n in ray_tpu.nodes())
    except Exception as e:  # diagnostics must never mask the assert
        return f"(node dump failed: {e})"


def test_pg_lease_return_after_remove_no_leak(tpu_cluster):
    """Regression: a worker lease granted from a PG bundle whose group is
    removed before the idle lease returns must NOT credit the node's main
    pool — ReleasePGBundles already returned the whole reserve (the +1 CPU
    phantom-capacity flake from round 4)."""
    import time

    total = ray_tpu.cluster_resources()["CPU"]
    _wait_cpu(lambda v: v >= total - 0.1)
    pg = placement_group([{"CPU": 2.0}], strategy="PACK")
    assert pg.ready(timeout=60)

    @ray_tpu.remote(num_cpus=1)
    def touch():
        return 1

    strat = PlacementGroupSchedulingStrategy(pg, placement_group_bundle_index=0)
    assert ray_tpu.get(touch.options(scheduling_strategy=strat).remote(),
                       timeout=60) == 1
    # remove the group while the 1-CPU lease is still idle-cached (TTL 2 s)
    remove_placement_group(pg)
    deadline = time.time() + 10.0
    while time.time() < deadline:
        avail = ray_tpu.available_resources().get("CPU", 0.0)
        assert avail <= total + 0.01, (
            f"available CPU {avail} exceeds total {total}: dead-PG lease "
            f"return double-credited the node pool")
        time.sleep(0.25)
    assert _wait_cpu(lambda v: v >= total - 0.1) >= total - 0.1


def test_slice_placement_group(tpu_cluster):
    spg = slice_placement_group(num_hosts=2)
    assert spg.ready(timeout=60)
    assert spg.num_chips == 8
    nodes_by_id = {n["node_id"]: n for n in ray_tpu.nodes()}
    bundle_nodes = spg.placement_group.bundle_nodes()
    assert len(set(bundle_nodes)) == 2
    slices = {nodes_by_id[nid]["labels"][LABEL_TPU_SLICE] for nid in bundle_nodes}
    assert len(slices) == 1 and slices.pop() == spg.slice_name

    # gang actors on the slice
    @ray_tpu.remote(num_tpus=4, num_cpus=1)
    class HostWorker:
        def node(self):
            return ray_tpu.get_runtime_context().get_node_id()

    strat0 = PlacementGroupSchedulingStrategy(spg.placement_group, 0)
    strat1 = PlacementGroupSchedulingStrategy(spg.placement_group, 1)
    w0 = HostWorker.options(scheduling_strategy=strat0).remote()
    w1 = HostWorker.options(scheduling_strategy=strat1).remote()
    n0 = ray_tpu.get(w0.node.remote(), timeout=120)
    n1 = ray_tpu.get(w1.node.remote(), timeout=120)
    assert {n0, n1} == set(bundle_nodes)
    remove_placement_group(spg.placement_group)


def test_pg_state_api(tpu_cluster):
    pg = placement_group([{"CPU": 1.0}], strategy="PACK", name="mypg")
    assert pg.ready(timeout=60)
    info = get_placement_group_state(pg)
    assert info["state"] == "CREATED" and info["name"] == "mypg"
    remove_placement_group(pg)

"""Autoscaler tests against the fake multi-node provider (reference:
python/ray/tests/test_autoscaler* with
autoscaler/_private/fake_multi_node/node_provider.py)."""

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import (
    Autoscaler,
    ClusterConfig,
    FakeMultiNodeProvider,
    NodeTypeConfig,
)
from ray_tpu.autoscaler.resource_demand_scheduler import (
    get_nodes_to_launch,
    get_nodes_to_terminate,
)
from ray_tpu.cluster_utils import Cluster


# ---------------------------------------------------------------------------
# pure bin-packing units (no cluster)
# ---------------------------------------------------------------------------


def _config(**kw):
    types = {
        "cpu4": NodeTypeConfig("cpu4", {"CPU": 4.0}, max_workers=5),
        "cpu16": NodeTypeConfig("cpu16", {"CPU": 16.0}, max_workers=2),
    }
    return ClusterConfig(node_types=types, **kw)


def test_scheduler_launches_for_unmet_demand():
    launch = get_nodes_to_launch(
        _config(), existing_by_type={}, node_available=[],
        demands=[{"CPU": 2.0}, {"CPU": 2.0}, {"CPU": 2.0}])
    # 3x CPU:2 pack onto 2x cpu4 (smallest fitting type), capped by
    # upscaling budget >= 1
    assert launch.get("cpu4", 0) >= 1


def test_scheduler_respects_existing_capacity():
    launch = get_nodes_to_launch(
        _config(), existing_by_type={"cpu4": 1},
        node_available=[{"CPU": 4.0}],
        demands=[{"CPU": 2.0}, {"CPU": 2.0}])
    assert launch == {}


def test_scheduler_min_workers():
    cfg = _config()
    cfg.node_types["cpu4"].min_workers = 2
    launch = get_nodes_to_launch(cfg, existing_by_type={}, node_available=[],
                                 demands=[])
    assert launch == {"cpu4": 2}


def test_scheduler_max_workers_cap():
    cfg = _config(upscaling_speed=100.0)
    launch = get_nodes_to_launch(
        cfg, existing_by_type={"cpu4": 5}, node_available=[],
        demands=[{"CPU": 4.0}] * 10)
    assert launch.get("cpu4", 0) == 0  # at max; big type picks up nothing
    # (cpu16 doesn't fit CPU:4? it does) -> cpu16 may take them
    assert launch.get("cpu16", 0) <= 2


def test_scheduler_big_shape_picks_big_type():
    launch = get_nodes_to_launch(
        _config(), existing_by_type={}, node_available=[],
        demands=[{"CPU": 12.0}])
    assert launch == {"cpu16": 1}


def test_scale_down_idle_above_min():
    cfg = _config(idle_timeout_s=5.0)
    cfg.node_types["cpu4"].min_workers = 1
    nodes = [
        {"node_type": "cpu4", "idle_s": 100.0, "used": False},
        {"node_type": "cpu4", "idle_s": 100.0, "used": False},
        {"node_type": "cpu4", "idle_s": 0.0, "used": True},
    ]
    victims = get_nodes_to_terminate(cfg, nodes)
    assert len(victims) == 2  # 3 nodes, min 1... but only 2 idle
    cfg.node_types["cpu4"].min_workers = 2
    victims = get_nodes_to_terminate(cfg, nodes)
    assert len(victims) == 1


def test_scheduler_selector_demand_needs_matching_type():
    types = {
        "plain": NodeTypeConfig("plain", {"CPU": 8.0}, max_workers=5),
        "tpu": NodeTypeConfig("tpu", {"CPU": 8.0, "TPU": 4.0},
                              labels={"accelerator": "v5e"}, max_workers=5),
    }
    cfg = ClusterConfig(node_types=types)
    # plenty of free CPU on an unlabeled node, but the selector targets v5e
    launch = get_nodes_to_launch(
        cfg, existing_by_type={"plain": 1},
        node_available=[{"available": {"CPU": 8.0}, "labels": {}}],
        demands=[{"shape": {"CPU": 1.0}, "selector": {"accelerator": "v5e"}}])
    assert launch == {"tpu": 1}


def test_tpu_slice_scales_as_gang():
    types = {"v5e-16": NodeTypeConfig(
        "v5e-16", {"CPU": 8.0, "TPU": 4.0}, hosts_per_slice=4, max_workers=2)}
    cfg = ClusterConfig(node_types=types, upscaling_speed=100.0)
    launch = get_nodes_to_launch(
        cfg, existing_by_type={}, node_available=[],
        demands=[{"TPU": 4.0}])
    assert launch == {"v5e-16": 1}  # one slice = 4 hosts


# ---------------------------------------------------------------------------
# end-to-end against a live cluster + fake provider
# ---------------------------------------------------------------------------


@pytest.fixture
def scaling_cluster():
    ray_tpu.shutdown()
    cluster = Cluster(head_node_args={"resources": {"CPU": 1.0}})
    ray_tpu.init(address=cluster.address)
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


def test_autoscaler_scales_up_for_pending_task(scaling_cluster):
    provider = FakeMultiNodeProvider(scaling_cluster)
    config = ClusterConfig(node_types={
        "worker": NodeTypeConfig("worker", {"CPU": 4.0, "BIG": 1.0},
                                 max_workers=3),
    })
    scaler = Autoscaler(config, provider, scaling_cluster.address)

    @ray_tpu.remote(resources={"BIG": 1.0}, num_cpus=1)
    def needs_big():
        return "scaled"

    ref = needs_big.remote()  # unplaceable: no BIG anywhere
    time.sleep(1.0)  # let the demand register in the GCS

    deadline = time.monotonic() + 60
    launched = False
    while time.monotonic() < deadline:
        status = scaler.step()
        if status["launched"] or launched:
            launched = True
            break
        time.sleep(0.5)
    assert launched, "autoscaler never launched a node for pending demand"
    assert ray_tpu.get(ref, timeout=120) == "scaled"


def test_autoscaler_scales_up_for_pending_placement_group(scaling_cluster):
    provider = FakeMultiNodeProvider(scaling_cluster)
    config = ClusterConfig(node_types={
        "worker": NodeTypeConfig("worker", {"CPU": 4.0}, max_workers=3),
    }, upscaling_speed=100.0)
    scaler = Autoscaler(config, provider, scaling_cluster.address)
    scaler.start(interval_s=0.5)
    try:
        from ray_tpu.util.placement_group import placement_group

        pg = placement_group([{"CPU": 3.0}, {"CPU": 3.0}], strategy="SPREAD")
        assert pg.ready(timeout=120)  # needs 2 new nodes
    finally:
        scaler.stop()
    assert len(provider.non_terminated_nodes()) >= 2


def test_autoscaler_scales_down_idle_node(scaling_cluster):
    provider = FakeMultiNodeProvider(scaling_cluster)
    config = ClusterConfig(node_types={
        "worker": NodeTypeConfig("worker", {"CPU": 2.0}, max_workers=3),
    }, idle_timeout_s=2.0)
    scaler = Autoscaler(config, provider, scaling_cluster.address)

    nodes = provider.create_nodes(config.node_types["worker"], 1)
    assert len(nodes) == 1
    scaling_cluster.wait_for_nodes(2)

    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        scaler.step()
        if not provider.non_terminated_nodes():
            break
        time.sleep(0.5)
    assert provider.non_terminated_nodes() == []
    alive = [n for n in ray_tpu.nodes() if n["alive"]]
    assert len(alive) == 1  # only the head remains

"""SAC + offline (BC/MARWIL) learning tests (reference tier:
rllib/tuned_examples run-to-reward assertions on tiny budgets)."""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=6)
    yield ray_tpu
    ray_tpu.shutdown()


def test_sac_pendulum_improves(cluster):
    from ray_tpu.rl import SAC, SACConfig

    cfg = SACConfig(num_env_runners=1, num_envs_per_runner=4,
                    rollout_length=64, warmup_steps=512,
                    updates_per_iteration=48, batch_size=128,
                    hidden=(64, 64), seed=3)
    algo = cfg.build()
    try:
        first = None
        best = -1e9
        for i in range(130):
            result = algo.train()
            ret = result["episode_return_mean"]
            # the return window only fills once episodes complete
            # (Pendulum truncates at 200 steps per env)
            if result["num_env_steps_sampled"] < 1280:
                continue
            if first is None:
                first = ret
            best = max(best, ret)
            if best > first + 400:
                break
        assert first is not None
        assert best > first + 400, (
            f"SAC did not improve: first={first:.1f} best={best:.1f}")
    finally:
        algo.stop()


def _expert_cartpole_data(n_episodes=40, seed=0):
    """Heuristic CartPole expert: push toward the pole's fall direction."""
    import gymnasium as gym

    env = gym.make("CartPole-v1")
    obs_l, act_l, rew_l, done_l = [], [], [], []
    for ep in range(n_episodes):
        obs, _ = env.reset(seed=seed + ep)
        done = False
        while not done:
            angle, ang_vel = obs[2], obs[3]
            action = 1 if (angle + 0.5 * ang_vel) > 0 else 0
            obs_l.append(obs)
            act_l.append(action)
            obs, rew, term, trunc, _ = env.step(action)
            rew_l.append(rew)
            done = term or trunc
            done_l.append(done)
    env.close()
    return {
        "obs": np.asarray(obs_l, np.float32),
        "actions": np.asarray(act_l, np.int32),
        "rewards": np.asarray(rew_l, np.float32),
        "dones": np.asarray(done_l, bool),
    }


def test_bc_imitates_expert(cluster):
    from ray_tpu.rl import BC, BCConfig

    data = _expert_cartpole_data()
    algo = BC(BCConfig(updates_per_iteration=64, eval_episodes=5), data)
    for _ in range(6):
        algo.train()
    score = algo.evaluate()["episode_return_mean"]
    assert score > 150, f"BC policy too weak: {score}"


def test_bc_from_data_layer_dataset(cluster):
    from ray_tpu import data as rdata
    from ray_tpu.rl import BC, BCConfig

    raw = _expert_cartpole_data(n_episodes=15)
    rows = [{"obs": raw["obs"][i], "actions": int(raw["actions"][i])}
            for i in range(len(raw["obs"]))]
    ds = rdata.from_items(rows, parallelism=4)
    algo = BC(BCConfig(updates_per_iteration=64, eval_episodes=4), ds)
    for _ in range(5):
        algo.train()
    assert algo.evaluate()["episode_return_mean"] > 120


def test_marwil_beats_mixed_data_bc(cluster):
    """MARWIL upweights good trajectories in a mixed expert/random dataset;
    plain BC on the same data imitates the average."""
    from ray_tpu.rl import BC, BCConfig, MARWIL, MARWILConfig

    expert = _expert_cartpole_data(n_episodes=15, seed=0)

    # random-policy data (poor returns)
    import gymnasium as gym

    env = gym.make("CartPole-v1")
    rng = np.random.default_rng(0)
    obs_l, act_l, rew_l, done_l = [], [], [], []
    for ep in range(25):
        obs, _ = env.reset(seed=100 + ep)
        done = False
        while not done:
            action = int(rng.integers(0, 2))
            obs_l.append(obs)
            act_l.append(action)
            obs, rew, term, trunc, _ = env.step(action)
            rew_l.append(rew)
            done = term or trunc
            done_l.append(done)
    env.close()
    mixed = {
        "obs": np.concatenate([expert["obs"], np.asarray(obs_l, np.float32)]),
        "actions": np.concatenate([expert["actions"],
                                   np.asarray(act_l, np.int32)]),
        "rewards": np.concatenate([expert["rewards"],
                                   np.asarray(rew_l, np.float32)]),
        "dones": np.concatenate([expert["dones"], np.asarray(done_l, bool)]),
    }

    marwil = MARWIL(MARWILConfig(updates_per_iteration=64, eval_episodes=5,
                                 beta=2.0), dict(mixed))
    for _ in range(8):
        marwil.train()
    marwil_score = marwil.evaluate()["episode_return_mean"]
    assert marwil_score > 100, f"MARWIL too weak on mixed data: {marwil_score}"


def test_sac_checkpoint_roundtrip(cluster, tmp_path):
    from ray_tpu.rl import SAC, SACConfig

    cfg = SACConfig(num_env_runners=1, num_envs_per_runner=2,
                    rollout_length=16, warmup_steps=0,
                    updates_per_iteration=2, batch_size=32, hidden=(32,))
    algo = cfg.build()
    try:
        algo.train()
        path = algo.save_checkpoint(str(tmp_path / "ck"))
        algo2 = cfg.build()
        try:
            algo2.restore_from_checkpoint(path)
        finally:
            algo2.stop()
    finally:
        algo.stop()

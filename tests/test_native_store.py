"""Native-tier unit tests: builds and runs the C++ arena-store test binary
against the same C ABI the Python binding loads (reference: the gtest
suites colocated with src/ray/object_manager/plasma)."""

import os
import subprocess
import tempfile

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(REPO, "src", "object_store")


@pytest.fixture(scope="module")
def test_binary(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("native") / "store_test")
    build = subprocess.run(
        ["g++", "-O1", "-std=c++17",
         os.path.join(SRC, "store.cc"), os.path.join(SRC, "store_test.cc"),
         "-o", out],
        capture_output=True, text=True, timeout=180)
    assert build.returncode == 0, build.stderr
    return out


def test_arena_store_native_suite(test_binary, tmp_path):
    base = os.path.join("/dev/shm", f"rtpu_ntest_{os.getpid()}")
    try:
        run = subprocess.run([test_binary, base], capture_output=True,
                             text=True, timeout=120)
        assert run.returncode == 0, f"{run.stdout}\n{run.stderr}"
        assert "OK" in run.stdout
    finally:
        for suffix in ".a .b .c .d .e .f".split():
            try:
                os.unlink(base + suffix)
            except OSError:
                pass

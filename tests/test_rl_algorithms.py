"""DQN / IMPALA / replay buffer tests (reference tier: rllib
tuned_examples run-to-reward, shrunk for CI)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rl import (DQN, IMPALA, DQNConfig, IMPALAConfig,
                        PrioritizedReplayBuffer, ReplayBuffer)


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield ray_tpu
    ray_tpu.shutdown()


def test_replay_buffer_ring():
    buf = ReplayBuffer(capacity=100, seed=0)
    for i in range(12):
        buf.add_batch({"x": np.full(10, i, np.float32)})
    assert len(buf) == 100
    s = buf.sample(64)
    assert s["x"].shape == (64,)
    # oldest chunk (i=0,1) was overwritten by i=10,11
    assert s["x"].min() >= 2.0


def test_prioritized_buffer_biases_sampling():
    buf = PrioritizedReplayBuffer(capacity=128, alpha=1.0, seed=0)
    buf.add_batch({"x": np.arange(128, dtype=np.float32)})
    idx = np.arange(128)
    # give item 7 overwhelming priority
    td = np.full(128, 1e-4)
    td[7] = 100.0
    buf.update_priorities(idx, td)
    s = buf.sample(256)
    frac = float((s["x"] == 7.0).mean())
    assert frac > 0.5, frac
    assert "weights" in s and s["weights"].shape == (256,)
    # weights for the over-sampled item are the smallest
    assert s["weights"].min() == pytest.approx(
        s["weights"][s["x"] == 7.0].min())


def test_dqn_cartpole_improves(cluster):
    algo = DQNConfig(
        env="CartPole-v1", num_env_runners=2, num_envs_per_runner=2,
        rollout_length=64, learning_starts=400, updates_per_iteration=48,
        epsilon_decay_steps=4000, target_update_freq=300, seed=3,
    ).build()
    returns = []
    for _ in range(55):
        m = algo.train()
        returns.append(m["episode_return_mean"])
    algo.stop()
    assert max(returns) > 60, returns


def test_dqn_checkpoint_roundtrip(cluster, tmp_path):
    cfg = DQNConfig(num_env_runners=1, num_envs_per_runner=1,
                    rollout_length=8, learning_starts=8,
                    updates_per_iteration=2, seed=0)
    algo = cfg.build()
    algo.train()
    ckpt = algo.save_checkpoint(str(tmp_path / "ck"))
    algo2 = cfg.build()
    algo2.restore_from_checkpoint(ckpt)
    a = algo.get_state()["params"]
    b = algo2.get_state()["params"]
    import jax

    jax.tree.map(lambda x, y: np.testing.assert_allclose(x, y), a, b)
    assert algo2.iteration == 1
    algo.stop()
    algo2.stop()


def test_impala_cartpole_improves(cluster):
    algo = IMPALAConfig(
        env="CartPole-v1", num_env_runners=2, num_envs_per_runner=4,
        rollout_length=64, num_rollouts_per_update=2, lr=1e-3,
        entropy_coef=0.01, seed=1,
    ).build()
    returns = []
    for _ in range(90):
        m = algo.train()
        returns.append(m["episode_return_mean"])
    algo.stop()
    # async off-policy lag is corrected by v-trace; must still learn
    assert max(returns) > 60, returns


def test_impala_rho_sane(cluster):
    algo = IMPALAConfig(num_env_runners=1, num_envs_per_runner=2,
                        rollout_length=16, num_rollouts_per_update=1,
                        seed=0).build()
    m = algo.train()
    # first update: behavior == target policy, so rho ~= 1
    assert m["mean_rho"] == pytest.approx(1.0, abs=0.05)
    algo.stop()

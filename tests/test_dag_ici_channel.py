"""Compiled ICI edge tier (reference:
experimental/channel/torch_tensor_accelerator_channel.py — stage hand-offs
ride the accelerator interconnect, not the host channel plane). CI runs the
same compiled ppermute on the virtual 8-device CPU mesh."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.dag import InputNode


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(resources={"CPU": 4.0})
    yield
    ray_tpu.shutdown()


def test_ici_edge_is_jitted_collective(cluster):
    @ray_tpu.remote(num_cpus=1.0)
    class Stage:
        def __init__(self):
            import jax
            from jax.sharding import Mesh

            self.mesh = Mesh(np.array(jax.devices()), ("ici",))
            self.n = self.mesh.devices.size

        def produce(self, scale):
            # shard i holds value i * scale
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            x = np.repeat(np.arange(self.n, dtype=np.float32), 4) \
                * np.float32(scale)
            return jax.device_put(
                x, NamedSharding(self.mesh, P("ici")))

        def consume(self, x):
            # after a shift-1 ppermute, shard i must hold (i-1) % n
            from ray_tpu.dag.device_channel import transfer_stats

            return {
                "vals": [float(np.asarray(s.data)[0])
                         for s in sorted(x.addressable_shards,
                                         key=lambda s: s.index)],
                "stats": transfer_stats(),
                "n": self.n,
            }

    stage = Stage.remote()
    with InputNode() as inp:
        mid = stage.produce.bind(inp).with_tensor_transport("ici", shift=1)
        out = stage.consume.bind(mid)
    dag = out.experimental_compile()
    try:
        for it in range(3):
            res = ray_tpu.get(dag.execute(float(it + 1)), timeout=180)
            n = res["n"]
            assert n == 8
            expect = [((i - 1) % n) * float(it + 1) for i in range(n)]
            assert res["vals"] == expect, (res["vals"], expect)
        stats = res["stats"]
        # the transfer compiled exactly once and ran every iteration —
        # a jitted collective, not a per-iteration RPC/serialization
        assert sum(stats["compiles"].values()) == 1, stats
        assert sum(stats["calls"].values()) >= 3, stats
    finally:
        dag.teardown()


def test_ici_edge_no_host_channel_allocated(cluster):
    """The annotated same-actor edge must not allocate any channel."""

    @ray_tpu.remote(num_cpus=1.0)
    class Stage:
        def __init__(self):
            import jax
            from jax.sharding import Mesh

            self.mesh = Mesh(np.array(jax.devices()), ("ici",))

        def a(self, x):
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            return jax.device_put(
                np.arange(8, dtype=np.float32) * x,
                NamedSharding(self.mesh, P("ici")))

        def b(self, x):
            return float(np.asarray(x).sum())

    stage = Stage.remote()
    with InputNode() as inp:
        out = stage.b.bind(
            stage.a.bind(inp).with_tensor_transport("ici", shift=1))
    dag = out.experimental_compile()
    try:
        # channels: one input + one output — nothing for the a->b edge
        names = {getattr(c, "name", "?") for c in dag._channels}
        assert len(names) == 2, names
        assert ray_tpu.get(dag.execute(2.0), timeout=180) == float(
            np.arange(8).sum() * 2.0)
    finally:
        dag.teardown()

"""Tests: ActorPool, Queue, DAG authoring/compile, channels, metrics,
state API, microbenchmark smoke."""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield ray_tpu
    ray_tpu.shutdown()


def test_actor_pool(cluster):
    from ray_tpu.util import ActorPool

    @ray_tpu.remote(num_cpus=0.5)
    class Worker:
        def double(self, x):
            return x * 2

    pool = ActorPool([Worker.remote() for _ in range(2)])
    out = pool.map(lambda a, v: a.double.remote(v), list(range(8)))
    assert sorted(out) == [i * 2 for i in range(8)]


def test_queue(cluster):
    from ray_tpu.util import Queue

    q = Queue()
    q.put({"a": 1})
    q.put({"a": 2})
    assert q.qsize() == 2
    assert q.get()["a"] == 1
    assert not q.empty()
    q.shutdown()


def test_dag_function_graph(cluster):
    from ray_tpu.dag import InputNode

    @ray_tpu.remote(num_cpus=0.5)
    def plus(a, b):
        return a + b

    @ray_tpu.remote(num_cpus=0.5)
    def times(a, b):
        return a * b

    with InputNode() as inp:
        dag = times.bind(plus.bind(inp, 1), 3)
    assert ray_tpu.get(dag.execute(4), timeout=60) == 15
    assert ray_tpu.get(dag.execute(0), timeout=60) == 3


def test_dag_actor_compiled(cluster):
    from ray_tpu.dag import InputNode

    @ray_tpu.remote(num_cpus=0.5)
    class Stage:
        def __init__(self, mult):
            self.mult = mult

        def apply(self, x):
            return x * self.mult

    with InputNode() as inp:
        s1 = Stage.bind(2)
        s2 = Stage.bind(10)
        dag = s2.apply.bind(s1.apply.bind(inp))
    compiled = dag.experimental_compile()
    assert ray_tpu.get(compiled.execute(3), timeout=60) == 60
    assert ray_tpu.get(compiled.execute(5), timeout=60) == 100
    compiled.teardown()


def test_channel_seqlock_roundtrip(cluster):
    from ray_tpu.dag.channels import Channel

    name = "test_chan_1"
    writer = Channel(name, capacity=1 << 16, create=True)
    reader = Channel(name)
    arr = np.arange(100, dtype=np.float64)
    writer.write({"arr": arr, "step": 1})
    out = reader.read(timeout=10)
    np.testing.assert_array_equal(out["arr"], arr)
    writer.write({"arr": arr * 2, "step": 2})
    out2 = reader.read(timeout=10)
    assert out2["step"] == 2
    writer.close(unlink=True)


def test_channel_cross_process(cluster):
    from ray_tpu.dag.channels import Channel

    name = "test_chan_xp"
    writer = Channel(name, capacity=1 << 16, create=True)

    @ray_tpu.remote(num_cpus=0.5)
    def consume(chan_name):
        from ray_tpu.dag.channels import Channel as C

        ch = C(chan_name)
        v = ch.read(timeout=30)
        return v["value"] + 1

    ref = consume.remote(name)
    import time

    time.sleep(0.3)
    writer.write({"value": 41})
    assert ray_tpu.get(ref, timeout=60) == 42
    writer.close(unlink=True)


def test_metrics(cluster):
    from ray_tpu.util.metrics import Counter, Gauge, Histogram, scrape_metrics

    c = Counter("test_requests", tag_keys=("route",))
    c.inc(2, {"route": "/a"})
    c.inc(3, {"route": "/a"})
    g = Gauge("test_depth")
    g.set(7)
    h = Histogram("test_latency", boundaries=[1, 10])
    h.observe(0.5)
    h.observe(50)
    snap = scrape_metrics()
    assert list(snap["test_requests"]["data"].values())[0] == 5
    assert list(snap["test_depth"]["data"].values())[0] == 7


def test_state_api(cluster):
    from ray_tpu.util import state

    @ray_tpu.remote
    class Marker:
        def ping(self):
            return 1

    m = Marker.options(name="state_marker").remote()
    ray_tpu.get(m.ping.remote(), timeout=60)
    actors = state.list_actors(state_filter="ALIVE")
    assert any(a["name"] == "state_marker" for a in actors)
    summary = state.summarize_cluster()
    assert summary["num_nodes"] == 1
    ray_tpu.kill(m)


def test_microbenchmark_smoke(cluster):
    from ray_tpu._private.microbenchmark import timeit

    @ray_tpu.remote(num_cpus=0.2)
    def f():
        return 1

    row = timeit("smoke", lambda: (ray_tpu.get(f.remote(), timeout=60), 1)[1],
                 duration=0.5)
    assert row["rate_per_s"] > 1

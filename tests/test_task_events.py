"""Task-event pipeline + causal tracing tests (reference tier:
task_event_buffer.cc -> GcsTaskManager -> `ray summary tasks`/dashboard;
trace-context propagation through the TaskSpec)."""

import json
import os
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.util import state, tracing


@pytest.fixture(scope="module")
def traced_cluster():
    ray_tpu.shutdown()
    os.environ["RAY_TPU_ENABLE_TRACING"] = "1"
    tracing._enabled = None  # re-read the flag
    worker = ray_tpu.init(num_cpus=4, include_dashboard=True)
    yield worker
    ray_tpu.shutdown()
    os.environ.pop("RAY_TPU_ENABLE_TRACING", None)
    tracing._enabled = None


def _wait_for(predicate, timeout=30, interval=0.5):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = predicate()
        if out:
            return out
        time.sleep(interval)
    return predicate()


# ---------------------------------------------------------------------------
# lifecycle golden
# ---------------------------------------------------------------------------


def test_lifecycle_states_recorded(traced_cluster):
    @ray_tpu.remote
    def lifecycle_probe(x):
        return x + 1

    assert ray_tpu.get(lifecycle_probe.remote(1), timeout=60) == 2

    def _done():
        # owner-side (FINISHED) and executor-side (RUNNING) events flush
        # independently: wait for the fully-merged record
        recs = [t for t in state.list_tasks(name="lifecycle_probe")
                if t["state"] == "FINISHED"
                and any(e["state"] == "RUNNING" for e in t["events"])]
        return recs or None

    recs = _wait_for(_done)
    assert recs, state.list_tasks()
    rec = recs[-1]
    # >= 4 timestamped transitions, in nominal lifecycle order
    states = [e["state"] for e in rec["events"]]
    assert len(rec["events"]) >= 4, rec
    for expected in ("SUBMITTED", "SCHEDULED", "RUNNING", "FINISHED"):
        assert expected in states, states
    order = [s for s in states
             if s in ("SUBMITTED", "LEASE_REQUESTED", "SCHEDULED",
                      "RUNNING", "FINISHED")]
    assert order == sorted(
        order, key=("SUBMITTED", "LEASE_REQUESTED", "SCHEDULED", "RUNNING",
                    "FINISHED").index), states
    ts = [e["ts"] for e in rec["events"]]
    assert ts == sorted(ts)
    assert rec["duration_s"] >= 0
    # the executing worker reported itself
    assert rec["worker"] and rec["node"]

    # get_task round-trips the same record
    got = state.get_task(rec["task_id"])
    assert got is not None and got["task_id"] == rec["task_id"]

    # summarize_tasks: the `ray summary tasks` analog
    summ = state.summarize_tasks()
    probe_counts = next((v for k, v in summ["per_function"].items()
                         if k.endswith("lifecycle_probe")), {})
    assert probe_counts.get("FINISHED", 0) >= 1, summ


def test_failed_then_retried_task_records_retry(traced_cluster, tmp_path):
    marker = str(tmp_path / "retry_marker")

    @ray_tpu.remote(max_retries=2, retry_exceptions=True)
    def flaky(path):
        if not os.path.exists(path):
            with open(path, "w") as f:
                f.write("x")
            raise ValueError("first attempt goes bang")
        return "recovered"

    assert ray_tpu.get(flaky.remote(marker), timeout=60) == "recovered"

    def _done():
        recs = [t for t in state.list_tasks(name="flaky")
                if t["state"] == "FINISHED" and t["attempt"] >= 1]
        return recs or None

    recs = _wait_for(_done)
    assert recs, state.list_tasks(name="flaky")
    rec = recs[-1]
    states = [e["state"] for e in rec["events"]]
    assert "RETRYING" in states, states
    assert rec["attempt"] >= 1
    # error summary of the failed attempt survives on the record
    assert "first attempt goes bang" in rec["error"], rec


def test_failed_task_is_terminal_failed(traced_cluster):
    @ray_tpu.remote(max_retries=0)
    def doomed():
        raise RuntimeError("persistent failure")

    with pytest.raises(Exception):
        ray_tpu.get(doomed.remote(), timeout=60)

    def _done():
        recs = [t for t in state.list_tasks(name="doomed")
                if t["state"] == "FAILED"]
        return recs or None

    recs = _wait_for(_done)
    assert recs
    assert "persistent failure" in recs[-1]["error"]


# ---------------------------------------------------------------------------
# trace tree + chrome flow events
# ---------------------------------------------------------------------------


def test_trace_tree_driver_actor_nested(traced_cluster, tmp_path):
    tracing.clear()

    @ray_tpu.remote
    def leaf_task(x):
        return x * 2

    @ray_tpu.remote
    class Middle:
        def relay(self, x):
            with tracing.profile("relay_inner"):
                return ray_tpu.get(leaf_task.remote(x))

    a = Middle.options(num_cpus=0.1).remote()
    assert ray_tpu.get(a.relay.remote(3), timeout=60) == 6

    # (cat, name-suffix) — function names are qualnames under pytest
    chain_keys = [("submit", "Middle.relay"), ("actor_task", "Middle.relay"),
                  ("user", "relay_inner"), ("submit", "leaf_task"),
                  ("task", "leaf_task")]

    def _find(spans, cat, suffix):
        return next((s for s in spans
                     if s.get("cat") == cat and s["name"].endswith(suffix)),
                    None)

    def _spans():
        spans = tracing.get_spans()
        if all(_find(spans, c, n) is not None for c, n in chain_keys):
            return spans
        return None

    spans = _wait_for(_spans)
    assert spans is not None, [(s.get("cat"), s["name"])
                               for s in tracing.get_spans()]
    chain = [_find(spans, c, n) for c, n in chain_keys]

    # one trace id covers driver -> actor -> nested task
    tids = {s["trace_id"] for s in chain}
    assert len(tids) == 1, [(s["name"], s.get("trace_id")) for s in chain]

    # parent links form the tree
    for child, parent in zip(chain[1:], chain[:-1]):
        assert child["parent_id"] == parent["span_id"], (child, parent)

    # chrome export renders the causality as flow-event pairs
    out = str(tmp_path / "trace.json")
    tracing.export_chrome_trace(out)
    events = json.load(open(out))["traceEvents"]
    starts = {e["id"] for e in events if e.get("ph") == "s"}
    finishes = {e["id"] for e in events if e.get("ph") == "f"}
    assert starts and starts == finishes
    # at least the two cross-process submit->execute edges flow
    assert len(starts) >= 2


# ---------------------------------------------------------------------------
# bounded GCS ring
# ---------------------------------------------------------------------------


def test_task_manager_ring_drop_oldest():
    from ray_tpu._private.gcs import GcsTaskManager

    mgr = GcsTaskManager(max_per_job=8, max_events_per_task=4)
    for i in range(50):
        mgr.add_events([{"task_id": f"t{i:04d}", "job_id": "job1",
                         "state": "SUBMITTED", "ts": float(i),
                         "name": "flood"}])
    assert len(mgr.jobs["job1"]) == 8
    # oldest dropped, newest kept, and the truncation is counted
    assert "t0000" not in mgr.jobs["job1"]
    assert "t0049" in mgr.jobs["job1"]
    assert mgr.dropped["job1"] == 42

    # per-task event list is bounded too
    for j in range(20):
        mgr.add_events([{"task_id": "t0049", "job_id": "job1",
                         "state": "RUNNING", "ts": 100.0 + j}])
    assert len(mgr.jobs["job1"]["t0049"]["events"]) == 4

    # reporter-side drops surface in the summary
    mgr.add_events([], dropped=7)
    summ = mgr.summarize()
    assert summ["dropped"]["_reporter"] == 7
    assert summ["dropped"]["job1"] == 42


def test_task_manager_merges_out_of_order_terminal():
    from ray_tpu._private.gcs import GcsTaskManager

    mgr = GcsTaskManager(max_per_job=8)
    mgr.add_events([
        {"task_id": "t1", "job_id": "j", "state": "FINISHED", "ts": 10.0},
        # late executor-side RUNNING must not resurrect the task
        {"task_id": "t1", "job_id": "j", "state": "RUNNING", "ts": 9.0},
    ])
    rec = mgr.get_task("t1")
    assert rec["state"] == "FINISHED"
    # but the record keeps the full (ts-sorted) history
    assert [e["state"] for e in rec["events"]] == ["RUNNING", "FINISHED"]


# ---------------------------------------------------------------------------
# always-on metrics flusher
# ---------------------------------------------------------------------------


def test_metrics_autoflush_to_dashboard(traced_cluster):
    from ray_tpu.util.metrics import Counter

    c = Counter("obs_autoflush_probe", "test counter")
    c.inc(3.0)

    address = traced_cluster.node_supervisor.dashboard_address
    assert address

    def _scrape():
        with urllib.request.urlopen(f"http://{address}/metrics",
                                    timeout=30) as r:
            body = r.read().decode()
        return body if "obs_autoflush_probe" in body else None

    # no publish_metrics() call anywhere: the flusher loop ships it
    body = _wait_for(_scrape, timeout=40)
    assert body is not None, "registry never appeared in /metrics"
    assert "obs_autoflush_probe 3.0" in body or \
        'obs_autoflush_probe{' in body

    # built-in instruments ride along: task latency histograms (tasks ran
    # in earlier tests of this module) with proper bucket series
    assert "ray_tpu_task_e2e_seconds" in body
    assert "ray_tpu_task_exec_seconds_bucket" in body
    assert 'le="+Inf"' in body
    # raylet-side gauges are flushed by the raylet's own loop
    assert "ray_tpu_object_store_bytes" in body
    assert "ray_tpu_raylet_lease_queue_depth" in body


def test_dashboard_tasks_endpoints(traced_cluster):
    @ray_tpu.remote
    def dash_probe():
        return 1

    assert ray_tpu.get(dash_probe.remote(), timeout=60) == 1
    address = traced_cluster.node_supervisor.dashboard_address

    def _tasks():
        with urllib.request.urlopen(
                f"http://{address}/api/tasks?name=dash_probe",
                timeout=30) as r:
            out = json.loads(r.read().decode())
        return out if any(t["state"] == "FINISHED" for t in out) else None

    tasks = _wait_for(_tasks)
    assert tasks, "no FINISHED dash_probe in /api/tasks"
    with urllib.request.urlopen(f"http://{address}/api/tasks/summary",
                                timeout=30) as r:
        summ = json.loads(r.read().decode())
    probe_counts = next((v for k, v in summ["per_function"].items()
                         if k.endswith("dash_probe")), {})
    assert probe_counts.get("FINISHED", 0) >= 1, summ


def test_set_enabled_override_survives_racing_env_read():
    """Regression (raylint RCE001, single-site lazy init): a set_enabled()
    override issued while another thread is mid-way through enabled()'s
    first env read must not be clobbered by that thread's stale result.
    Pre-fix, the unlocked check-then-act in enabled() lost exactly this
    update; the double-checked lock orders the override after the read."""
    import os as real_os
    import threading

    from ray_tpu._private import task_events

    entered = threading.Event()
    release = threading.Event()

    class SlowEnviron:
        def get(self, key, default=None):
            if key == "RAY_TPU_TASK_EVENTS":
                entered.set()
                release.wait(10)
            return real_os.environ.get(key, default)

    class FakeOS:
        environ = SlowEnviron()

    task_events.set_enabled(None)  # force the lazy env re-read
    task_events.os = FakeOS()  # only task_events' view of os.environ
    try:
        reader = threading.Thread(target=task_events.enabled)
        reader.start()
        assert entered.wait(10), "reader never reached the env read"
        overrider = threading.Thread(
            target=task_events.set_enabled, args=(False,))
        overrider.start()
        time.sleep(0.1)  # let the override reach (and block on) _lock
        release.set()
        reader.join(10)
        overrider.join(10)
        assert not reader.is_alive() and not overrider.is_alive()
        assert task_events.enabled() is False
    finally:
        task_events.os = real_os
        task_events.set_enabled(None)

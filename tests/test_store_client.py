"""Unit tests for the GCS store client (reference tier:
src/ray/gcs/store_client/tests)."""

import os
import pickle

from ray_tpu._private.store_client import FileStoreClient, InMemoryStoreClient


def test_in_memory_basics():
    s = InMemoryStoreClient()
    s.put("t", "a", b"1")
    assert s.get("t", "a") == b"1"
    assert s.all("t") == {"a": b"1"}
    s.delete("t", "a")
    assert s.get("t", "a") is None


def test_file_store_reload(tmp_path):
    d = str(tmp_path / "store")
    s = FileStoreClient(d)
    s.put("actors", "x", b"alive")
    s.put("actors", "y", b"dead")
    s.delete("actors", "y")
    s.put("kv", "k", b"v")
    s.close()

    s2 = FileStoreClient(d)
    assert s2.all("actors") == {"x": b"alive"}
    assert s2.get("kv", "k") == b"v"
    s2.close()


def test_file_store_torn_tail_truncated(tmp_path):
    d = str(tmp_path / "store")
    s = FileStoreClient(d)
    s.put("t", "good", b"1")
    s.close()
    # simulate a crash mid-append: garbage half-record at the tail
    with open(os.path.join(d, FileStoreClient.JOURNAL), "ab") as f:
        f.write((1 << 20).to_bytes(4, "big") + b"partial")
    s2 = FileStoreClient(d)
    assert s2.get("t", "good") == b"1"
    # the torn tail was truncated, so new appends replay cleanly
    s2.put("t", "after", b"2")
    s2.close()
    s3 = FileStoreClient(d)
    assert s3.all("t") == {"good": b"1", "after": b"2"}
    s3.close()


def test_file_store_compaction(tmp_path):
    d = str(tmp_path / "store")
    s = FileStoreClient(d)
    s.COMPACT_EVERY = 10
    for i in range(25):
        s.put("t", f"k{i % 5}", pickle.dumps(i))
    s.close()
    assert os.path.exists(os.path.join(d, FileStoreClient.SNAPSHOT))
    s2 = FileStoreClient(d)
    assert len(s2.all("t")) == 5
    assert pickle.loads(s2.get("t", "k4")) == 24
    s2.close()


def test_corrupt_snapshot_is_quarantined(tmp_path):
    d = str(tmp_path / "store")
    s = FileStoreClient(d)
    s.COMPACT_EVERY = 2
    s.put("t", "a", b"1")
    s.put("t", "b", b"2")  # triggers compaction -> snapshot exists
    s.put("t", "c", b"3")  # lands in the fresh journal
    s.close()
    snap = os.path.join(d, FileStoreClient.SNAPSHOT)
    with open(snap, "wb") as f:
        f.write(b"garbage")
    s2 = FileStoreClient(d)
    # snapshot contents lost (quarantined), journal-only records survive
    assert s2.get("t", "c") == b"3"
    assert os.path.exists(snap + ".corrupt")
    s2.close()

"""Device-fed data iteration + structured Dataset stats (reference:
python/ray/data/iterator.py:106,269 iter_torch_batches device prefetch;
data/_internal/stats.py per-op metrics)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rdata


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(resources={"CPU": 4.0})
    yield
    ray_tpu.shutdown()


def test_iter_device_batches_yields_device_arrays(cluster):
    ds = rdata.range(400).map_batches(
        lambda b: {"x": np.asarray(b["id"], dtype=np.float32) * 2.0})
    total = 0
    import jax

    for batch in ds.iter_device_batches(batch_size=64, device_prefetch=2):
        assert isinstance(batch["x"], jax.Array)
        total += int(batch["x"].shape[0])
        assert float(batch["x"][0]) % 2.0 == 0.0
    assert total == 400


def test_device_prefetch_overlaps_consumer_compute(cluster):
    """With a deliberately slow consumer, prefetched iteration overlaps the
    producer's block fetch + H2D with the consumer's step; unprefetched
    iteration serializes them."""

    def slow_map(b):
        time.sleep(0.03)
        return {"x": np.asarray(b["id"], dtype=np.float32)}

    def run(depth):
        ds = rdata.range(1200, parallelism=12).map_batches(slow_map)
        t0 = time.perf_counter()
        n = 0
        for batch in ds.iter_device_batches(batch_size=100,
                                            device_prefetch=depth):
            time.sleep(0.03)  # consumer "compute"
            n += batch["x"].shape[0]
        assert n == 1200
        return time.perf_counter() - t0

    serial = run(1)  # depth-1 still pipelines one ahead; warms compiles
    fast = run(3)
    # the producer thread + deeper window must not be slower; usually it
    # overlaps a real fraction of the consumer sleep
    assert fast < serial * 1.25, (fast, serial)


def test_stats_data_per_op(cluster):
    ds = rdata.range(300).map_batches(
        lambda b: {"x": np.asarray(b["id"]) + 1})
    list(ds.iter_batches(batch_size=50))
    stats = ds.stats_data()
    assert stats, "expected per-op stats"
    assert any(s["rows_out"] >= 300 for s in stats), stats
    for s in stats:
        assert {"op", "tasks", "rows_out", "bytes_out",
                "task_wall_s", "wall_s"} <= set(s)
    # string form still renders
    assert "rows" in ds.stats()


def test_stats_visible_via_state_api(cluster):
    from ray_tpu.util.state import list_dataset_stats

    ds = rdata.range(100).map_batches(lambda b: {"y": np.asarray(b["id"])})
    list(ds.iter_batches(batch_size=25))
    entries = list_dataset_stats()
    assert entries, "dataset stats should be published to the state API"
    assert any(any(op["rows_out"] >= 100 for op in e["ops"])
               for e in entries)

"""Advanced Tune tests: HyperBand, median stopping, PBT, searchers
(reference tier: tune/tests/test_trial_scheduler*.py, test_searchers.py)."""

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune import (
    HyperBandScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
    QuasiRandomSearcher,
    TPESearcher,
    TuneConfig,
    Tuner,
)


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    yield ray_tpu
    ray_tpu.shutdown()


def _trainable(config):
    """Score converges toward `quality`; bad configs plateau low."""
    score = 0.0
    for i in range(12):
        score = score + (config["quality"] - score) * 0.5
        tune.report({"score": score})
    return {"score": score}


def test_hyperband_finds_best_and_prunes(cluster):
    tuner = Tuner(
        _trainable,
        param_space={"quality": tune.grid_search([0.1, 0.3, 0.5, 0.7, 1.0])},
        tune_config=TuneConfig(
            metric="score", mode="max", num_samples=1,
            scheduler=HyperBandScheduler(metric="score", mode="max", max_t=12),
        ),
        resources_per_trial={"CPU": 1.0},
    )
    grid = tuner.fit()
    best = grid.get_best_result()
    assert best.config["quality"] == 1.0
    assert any(r.stopped_early for r in grid.results)


def test_median_stopping(cluster):
    tuner = Tuner(
        _trainable,
        param_space={"quality": tune.grid_search([0.05, 0.1, 0.9, 0.95, 1.0])},
        tune_config=TuneConfig(
            metric="score", mode="max",
            scheduler=MedianStoppingRule(metric="score", mode="max",
                                         grace_period=3),
        ),
    )
    grid = tuner.fit()
    assert grid.get_best_result().config["quality"] == 1.0


def _pbt_trainable(config):
    """Linear progress whose rate is the (mutable) lr; checkpoints carry
    accumulated progress across exploits."""
    ckpt = config.get("__checkpoint__") or {"progress": 0.0}
    progress = ckpt["progress"]
    for i in range(12):
        progress += config["lr"]
        tune.report({"score": progress}, checkpoint={"progress": progress})
    return {"score": progress}


def test_pbt_exploits_good_configs(cluster):
    pbt = PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=4,
        hyperparam_mutations={"lr": (0.001, 1.0)}, seed=1)
    tuner = Tuner(
        _pbt_trainable,
        param_space={"lr": tune.grid_search([0.001, 0.002, 0.5, 1.0])},
        tune_config=TuneConfig(metric="score", mode="max", scheduler=pbt,
                               max_concurrent_trials=4),
    )
    grid = tuner.fit()
    # every surviving trial should end far better than the worst seed
    # configs could reach alone (0.001 * 12 = 0.012)
    best = grid.get_best_result()
    assert float(best.metrics["score"]) > 1.0
    # at least one exploit happened: some trial ran with a config not in
    # the original grid (mutated by 0.8x/1.2x)
    seen = {r.config["lr"] for r in grid.results}
    assert any(lr not in (0.001, 0.002, 0.5, 1.0) for lr in seen) or \
        any("__checkpoint__" in r.config for r in grid.results)


def test_quasi_random_searcher(cluster):
    searcher = QuasiRandomSearcher(
        {"quality": tune.uniform(0.0, 1.0)}, num_samples=6)
    tuner = Tuner(
        _trainable,
        param_space={},
        tune_config=TuneConfig(metric="score", mode="max",
                               search_alg=searcher),
    )
    grid = tuner.fit()
    assert len(grid) == 6
    qs = [r.config["quality"] for r in grid.results]
    assert len(set(round(q, 6) for q in qs)) == 6  # spread, not repeated


def test_tpe_searcher_improves_over_warmup(cluster):
    searcher = TPESearcher(
        {"quality": tune.uniform(0.0, 1.0)}, num_samples=12,
        metric="score", mode="max", n_warmup=4, seed=3)
    tuner = Tuner(
        _trainable,
        param_space={},
        tune_config=TuneConfig(metric="score", mode="max",
                               search_alg=searcher, max_concurrent_trials=2),
    )
    grid = tuner.fit()
    assert len(grid) == 12
    # results complete out of order: sort by suggestion order (trial id)
    ordered = sorted((r for r in grid.results if r.error is None),
                     key=lambda r: r.trial_id)
    scores = [float(r.metrics["score"]) for r in ordered]
    assert len(scores) >= 10
    warmup_avg = sum(scores[:4]) / 4
    later = scores[6:]
    later_avg = sum(later) / len(later)
    assert later_avg >= warmup_avg * 0.8  # guided phase shouldn't collapse

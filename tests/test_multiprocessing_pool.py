"""multiprocessing.Pool shim tests (reference tier:
python/ray/tests/test_multiprocessing.py basics)."""

import pytest

import ray_tpu
from ray_tpu.util.multiprocessing import Pool


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield ray_tpu
    ray_tpu.shutdown()


def _sq(x):
    return x * x


def _addmul(a, b, c=1):
    return (a + b) * c


def test_map_and_starmap(cluster):
    with Pool(processes=3) as pool:
        assert pool.map(_sq, range(8)) == [x * x for x in range(8)]
        assert pool.starmap(_addmul, [(1, 2), (3, 4)]) == [3, 7]


def test_apply_and_async(cluster):
    pool = Pool(processes=2)
    assert pool.apply(_addmul, (2, 3), {"c": 10}) == 50
    res = pool.apply_async(_sq, (9,))
    assert res.get(timeout=60) == 81
    assert res.ready()


def test_imap_unordered(cluster):
    pool = Pool(processes=3)
    out = sorted(pool.imap_unordered(_sq, range(6)))
    assert out == [x * x for x in range(6)]


def test_initializer(cluster):
    import os

    def init_env():
        os.environ["POOL_MARK"] = "yes"

    def read_env(_):
        import os

        return os.environ.get("POOL_MARK", "no")

    with Pool(processes=2, initializer=init_env) as pool:
        assert pool.map(read_env, [1, 2]) == ["yes", "yes"]

"""Worker provisioning plane: zygote prefork pool, warm-worker adoption,
batched lease grants, and failure fallbacks (reference: worker_pool.h
prestart/adoption behind RequestWorkerLease, node_manager.cc:1820).

These tests boot a real GCS + raylet IN-PROCESS (one asyncio loop) and talk
to the raylet over its actual RPC surface; workers are real processes
forked from the zygote (or cold-spawned on the fallback paths).
"""

import asyncio
import os
import signal
import time

import pytest

import ray_tpu
from ray_tpu._private import wire
from ray_tpu._private.gcs import GcsServer
from ray_tpu._private.raylet import Raylet
from ray_tpu._private.rpc import RetryingRpcClient


def run(coro, timeout=120.0):
    return asyncio.run(asyncio.wait_for(coro, timeout))


async def boot(resources=None, prestart=0, warm=0):
    os.environ["RAY_TPU_PRESTART_WORKERS"] = str(prestart)
    os.environ["RAY_TPU_WORKER_POOL_WARM_TARGET"] = str(warm)
    gcs = GcsServer()
    gcs_addr = await gcs.start()
    raylet = Raylet(gcs_address=gcs_addr, resources=resources or {"CPU": 8.0})
    await raylet.start()
    client = RetryingRpcClient(raylet.server.address)
    return gcs, raylet, client


async def teardown(gcs, raylet, client):
    await client.close()
    await raylet.stop()
    await gcs.stop()
    os.environ.pop("RAY_TPU_PRESTART_WORKERS", None)
    os.environ.pop("RAY_TPU_WORKER_POOL_WARM_TARGET", None)


async def wait_warm(raylet, n, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        warm = [w for w in raylet.idle_workers
                if w.job_hex is None and not w.renv_hash]
        if len(warm) >= n:
            return warm
        await asyncio.sleep(0.05)
    raise AssertionError(
        f"warm pool never reached {n} (have {len(raylet.idle_workers)})")


async def request_lease(client, resources=None, count=1, renv=None):
    return wire.loads(await client.call("RequestWorkerLease", wire.dumps({
        "resources": resources or {"CPU": 1.0},
        "job_id": None,
        "count": count,
        "runtime_env": renv,
    }), timeout=90.0))


def test_lease_adoption_reuses_prestarted_worker():
    """A granted lease must ADOPT a warm registered worker: same pid, no
    new process, counted as a pool hit."""
    async def body():
        gcs, raylet, client = await boot(warm=1)
        try:
            warm = await wait_warm(raylet, 1)
            warm_pids = {w.pid for w in warm}
            nworkers = len(raylet.workers)
            hits0 = raylet.provisioner.stats["hits"]
            reply = await request_lease(client)
            assert reply["status"] == "granted", reply
            assert reply["worker_pid"] in warm_pids, (
                "lease did not adopt the prestarted worker")
            assert raylet.provisioner.stats["hits"] == hits0 + 1
            # adoption spawned nothing (replenish may add more later, but
            # the granted worker itself is the old process)
            assert reply["worker_pid"] in {w.pid for w in raylet.workers.values()}
            assert len(raylet.workers) >= nworkers
            await client.call("ReturnWorkerLease", wire.dumps(
                {"lease_id": reply["lease_id"]}))
        finally:
            await teardown(gcs, raylet, client)
    run(body())


def test_renv_mismatch_bypasses_warm_pool():
    """A lease carrying a runtime env must NOT adopt a default-env warm
    worker: the pool is keyed by renv hash; a fresh dedicated worker is
    spawned and the warm one stays idle."""
    async def body():
        gcs, raylet, client = await boot(warm=1)
        try:
            warm = await wait_warm(raylet, 1)
            warm_pids = {w.pid for w in warm}
            misses0 = raylet.provisioner.stats["misses"]
            reply = await request_lease(
                client, renv={"env_vars": {"PROV_TEST": "1"}})
            assert reply["status"] == "granted", reply
            assert reply["worker_pid"] not in warm_pids, (
                "runtime-env lease adopted a default-env warm worker")
            assert raylet.provisioner.stats["misses"] == misses0 + 1
            # the warm worker was not consumed
            assert any(w.pid in warm_pids for w in raylet.idle_workers)
            await client.call("ReturnWorkerLease", wire.dumps(
                {"lease_id": reply["lease_id"]}))
        finally:
            await teardown(gcs, raylet, client)
    run(body())


def test_batched_multi_grant_vs_per_task():
    """count=N returns up to N grants in ONE reply (distinct leases on
    distinct warm workers, resources debited N times); count=1 keeps the
    single-grant shape."""
    async def body():
        gcs, raylet, client = await boot(warm=3)
        try:
            await wait_warm(raylet, 3)
            cpus0 = raylet.available["CPU"]
            reply = await request_lease(client, count=3)
            assert reply["status"] == "granted", reply
            extras = reply.get("extra_grants") or []
            assert len(extras) == 2, f"expected 2 extra grants, got {extras}"
            grants = [reply] + extras
            lease_ids = {g["lease_id"] for g in grants}
            pids = {g["worker_pid"] for g in grants}
            assert len(lease_ids) == 3 and len(pids) == 3
            assert raylet.available["CPU"] == cpus0 - 3.0
            for g in grants:
                await client.call("ReturnWorkerLease", wire.dumps(
                    {"lease_id": g["lease_id"]}))
            assert raylet.available["CPU"] == cpus0
            # per-task shape: count=1 never carries extra grants
            r1 = await request_lease(client, count=1)
            assert r1["status"] == "granted" and "extra_grants" not in r1
            await client.call("ReturnWorkerLease", wire.dumps(
                {"lease_id": r1["lease_id"]}))
        finally:
            await teardown(gcs, raylet, client)
    run(body())


def test_zygote_crash_respawns_and_cold_spawn_fallback():
    """Killing the zygote must not break leasing: the next spawn falls back
    to cold Popen, and the provisioner respawns the zygote in the
    background (counted in zygote_restarts)."""
    async def body():
        gcs, raylet, client = await boot(warm=0)
        try:
            prov = raylet.provisioner
            # zygote boots in the background; wait for it before crashing it
            deadline = time.monotonic() + 60
            while not prov.zygote_alive and time.monotonic() < deadline:
                await asyncio.sleep(0.05)
            assert prov.zygote_alive, "zygote never came up after start"
            await prov.crash_zygote_for_test()
            # lease immediately: pool empty + zygote dead -> cold spawn
            reply = await request_lease(client)
            assert reply["status"] == "granted", reply
            assert prov.stats["cold_spawns"] >= 1 or prov.zygote_alive, (
                "neither cold fallback nor a respawned zygote served the "
                f"lease: {prov.stats}")
            # the respawn loop brings the zygote back
            deadline = time.monotonic() + 60
            while not prov.zygote_alive and time.monotonic() < deadline:
                await asyncio.sleep(0.1)
            assert prov.zygote_alive, "zygote never respawned"
            assert prov.stats["zygote_restarts"] >= 1
            # and the respawned zygote serves forks again
            pid = await prov.fork_worker(None)
            assert pid is not None
            os.kill(pid, signal.SIGKILL)
            await client.call("ReturnWorkerLease", wire.dumps(
                {"lease_id": reply["lease_id"]}))
        finally:
            await teardown(gcs, raylet, client)
    run(body())


def test_oom_kill_of_adopted_worker_releases_leases():
    """When the memory monitor kills an adopted worker, the monitor loop
    must release its leases (credit the pool) and WasWorkerOOM must
    attribute the death."""
    async def body():
        gcs, raylet, client = await boot(warm=1)
        try:
            await wait_warm(raylet, 1)
            cpus0 = raylet.available["CPU"]
            reply = await request_lease(client)
            assert reply["status"] == "granted", reply
            assert raylet.available["CPU"] == cpus0 - 1.0
            w = raylet.workers[reply["worker_pid"]]
            # simulate the memory monitor's kill path: record + SIGKILL
            raylet.oom_kills[w.address] = time.monotonic()
            os.kill(w.pid, signal.SIGKILL)
            deadline = time.monotonic() + 30
            while reply["lease_id"] in raylet.leases \
                    and time.monotonic() < deadline:
                await asyncio.sleep(0.1)
            assert reply["lease_id"] not in raylet.leases, (
                "lease not released after the adopted worker died")
            assert raylet.available["CPU"] == cpus0
            assert w.pid not in raylet.workers
            oom = wire.loads(await client.call("WasWorkerOOM", wire.dumps(
                {"worker_address": w.address})))
            assert oom["oom"] is True
        finally:
            await teardown(gcs, raylet, client)
    run(body())


def test_forked_worker_runs_tasks_end_to_end():
    """Full-stack sanity: a driver on a zygote-backed cluster runs tasks
    and actors on adopted workers, and the pool stats surface through
    GetNodeStats."""
    ray_tpu.shutdown()
    os.environ["RAY_TPU_WORKER_POOL_WARM_TARGET"] = "2"
    try:
        ray_tpu.init()

        @ray_tpu.remote(num_cpus=0.1)
        def pid(i):
            import os as _os

            return _os.getpid()

        @ray_tpu.remote(num_cpus=0.1)
        class A:
            def ping(self):
                return "pong"

        pids = ray_tpu.get([pid.remote(i) for i in range(20)], timeout=120)
        assert len(pids) == 20
        actors = [A.remote() for _ in range(4)]
        assert ray_tpu.get([a.ping.remote() for a in actors],
                           timeout=120) == ["pong"] * 4
        from ray_tpu._private.worker import _global_worker

        stats = _global_worker._run(_global_worker.raylet.call(
            "GetNodeStats", wire.dumps({})), 30.0)
        pool = wire.loads(stats)["worker_pool"]
        assert pool["enabled"] and pool["zygote_alive"]
        assert pool["hits"] + pool["misses"] > 0
        assert pool["forks"] >= 1, pool
    finally:
        ray_tpu.shutdown()
        os.environ.pop("RAY_TPU_WORKER_POOL_WARM_TARGET", None)

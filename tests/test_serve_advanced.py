"""Serve autoscaling / long-poll / multiplexing tests (reference tier:
serve/tests/test_autoscaling_policy.py, test_long_poll.py,
test_multiplex.py)."""

import time

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=10)
    yield ray_tpu
    try:
        serve.shutdown()
    except Exception:
        pass
    ray_tpu.shutdown()


def test_autoscaling_up_and_down(cluster):
    @serve.deployment(autoscaling_config={
        "min_replicas": 1, "max_replicas": 3,
        "target_ongoing_requests": 1.0,
        "upscale_delay_s": 0.5, "downscale_delay_s": 1.5})
    class Slow:
        def __call__(self, body):
            time.sleep(0.4)
            return "ok"

    handle = serve.run(Slow.bind())
    assert serve.status()["Slow"]["num_replicas"] == 1

    # sustained burst -> scale up
    refs = [handle.remote({}) for _ in range(24)]
    deadline = time.monotonic() + 60
    scaled_up = False
    while time.monotonic() < deadline:
        if serve.status()["Slow"]["target"] >= 2:
            scaled_up = True
            break
        time.sleep(0.3)
    assert scaled_up, f"never scaled up: {serve.status()}"
    ray_tpu.get(refs, timeout=120)

    # idle -> scale back down to min
    deadline = time.monotonic() + 60
    scaled_down = False
    while time.monotonic() < deadline:
        if serve.status()["Slow"]["target"] == 1:
            scaled_down = True
            break
        time.sleep(0.5)
    assert scaled_down, f"never scaled down: {serve.status()}"
    serve.delete("Slow")


def test_long_poll_topology_updates(cluster):
    @serve.deployment(num_replicas=1)
    def echo(body):
        return body

    handle = serve.run(echo.bind(), name="lp_echo")
    v0 = handle._version
    # redeploy with more replicas; a long-poll wakes when topology changes
    import threading

    changed = {}

    def watch():
        # interim bumps (health-driven replacements) may wake the poll
        # before the redeploy lands; keep polling until 2 replicas appear
        deadline = time.monotonic() + 40
        result = False
        while time.monotonic() < deadline:
            result = handle._long_poll_refresh(timeout=10.0) or result
            if len(handle._replicas) == 2:
                break
        changed["result"] = result

    t = threading.Thread(target=watch)
    t.start()
    time.sleep(0.3)
    serve.run(echo.options(num_replicas=2).bind(), name="lp_echo")
    t.join(timeout=50)
    assert not t.is_alive()
    assert changed["result"] is True
    assert handle._version != v0
    assert len(handle._replicas) == 2
    serve.delete("lp_echo")


def test_multiplexed_models(cluster):
    @serve.deployment(num_replicas=2)
    class Zoo:
        def __init__(self):
            self.loads = []

        @serve.multiplexed(max_num_models_per_replica=2)
        async def get_model(self, model_id: str):
            self.loads.append(model_id)
            return {"id": model_id, "bias": len(self.loads)}

        async def __call__(self, body):
            model_id = serve.get_multiplexed_model_id()
            model = await self.get_model(model_id)
            return {"model": model["id"], "loads": list(self.loads)}

    handle = serve.run(Zoo.bind())
    m1 = handle.options(multiplexed_model_id="m1")
    outs = ray_tpu.get([m1.remote({}) for _ in range(4)], timeout=120)
    assert all(o["model"] == "m1" for o in outs)
    # same id -> same replica -> loaded exactly once
    assert all(o["loads"].count("m1") == 1 for o in outs)

    m2 = handle.options(multiplexed_model_id="m2")
    out2 = ray_tpu.get(m2.remote({}), timeout=120)
    assert out2["model"] == "m2"
    serve.delete("Zoo")


def test_redeploy_version_monotonic(cluster):
    @serve.deployment(num_replicas=1)
    def f(body):
        return 1

    h1 = serve.run(f.bind(), name="vmono")
    v1 = h1._version
    h2 = serve.run(f.options(num_replicas=2).bind(), name="vmono")
    assert h2._version > v1  # never collides across redeploys
    serve.delete("vmono")


def test_multiplexed_single_flight(cluster):
    @serve.deployment(num_replicas=1)
    class Zoo:
        def __init__(self):
            self.loads = 0

        @serve.multiplexed(max_num_models_per_replica=2)
        async def get_model(self, model_id: str):
            import asyncio as aio

            self.loads += 1
            await aio.sleep(0.3)  # slow load window for the race
            return model_id

        async def __call__(self, body):
            await self.get_model("m")
            return self.loads

    handle = serve.run(Zoo.bind(), name="sflight")
    outs = ray_tpu.get([handle.remote({}) for _ in range(4)], timeout=120)
    assert max(outs) == 1, f"model loaded {max(outs)} times concurrently"
    serve.delete("sflight")


def test_num_replicas_conflict_rejected(cluster):
    with pytest.raises(ValueError, match="mutually exclusive"):
        serve.deployment(num_replicas=3,
                         autoscaling_config={"min_replicas": 1})(lambda b: b)
    with pytest.raises(ValueError, match="unknown autoscaling_config"):
        serve.deployment(autoscaling_config={"max_replica": 2})(lambda b: b)


def test_multiplexed_lru_eviction(cluster):
    @serve.deployment(num_replicas=1)
    class Zoo:
        def __init__(self):
            self.loads = []

        @serve.multiplexed(max_num_models_per_replica=2)
        async def get_model(self, model_id: str):
            self.loads.append(model_id)
            return model_id

        async def __call__(self, body):
            await self.get_model(body["m"])
            return list(self.loads)

    handle = serve.run(Zoo.bind(), name="lru_zoo")
    ray_tpu.get(handle.remote({"m": "a"}), timeout=120)
    ray_tpu.get(handle.remote({"m": "b"}), timeout=60)
    ray_tpu.get(handle.remote({"m": "c"}), timeout=60)  # evicts "a"
    loads = ray_tpu.get(handle.remote({"m": "a"}), timeout=60)  # reload
    assert loads == ["a", "b", "c", "a"]
    serve.delete("lru_zoo")
